"""Plan canonicalization: parameter lifting, safety classification, and
shape-keyed fingerprints.

The corpus renders 99 templates into hundreds of SQL texts that differ
only in substituted literals (dsqgen semantics, PAPER.md §3).  Text-keyed
compile caches treat every rendering as a brand-new program; this pass
proves, statically, which texts share plan *structure* and which literals
are safe to hoist into runtime parameters, so one compiled XLA program
serves every stream permutation and every RNGSEED.

``canonicalize(optimized_plan)`` walks the plan bottom-up and replaces
each literal with a typed parameter slot (:class:`ndstpu.engine.expr.Param`
/ :class:`~ndstpu.engine.expr.InParam`), emitting:

* a **canonical fingerprint** — sha256 of the structural tree with slot
  markers in place of values (process-stable, keys the compile caches),
* a **binding list** — slot → original literal, resolved parameter type,
  and the source column the literal predicates (schema lookup shared with
  ``typecheck.py``),
* a **safety classification** per slot: *runtime-bindable* slots stay
  :class:`Param` in the executed plan and their values travel as
  execution inputs; *shape-affecting* slots (``LIMIT n``, date-interval
  widths, bounded CASE values, host-static function arguments, literals
  inside pre-resolved subqueries) are substituted back as concrete
  literals and their values join the cache key as a residual signature,
  each carrying a stable NDS4xx diagnostic.

Classification errors are a *performance* hazard, never a correctness
hazard: a value wrongly classified bindable still executes through the
same expression kernels as a broadcast column, and the executor's
recorded capacity/branch guards force rediscovery whenever a new binding
busts the discovered size plan (`jaxexec._capacity_for` ok-checks).  A
value wrongly classified shape-affecting merely costs an extra compile.

Import-hygienic like the rest of ``ndstpu.analysis``: numpy only, no jax,
no engine executors — safe for CI lint and doc tooling.
"""

from __future__ import annotations

import dataclasses
import hashlib
import re
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

from ndstpu.engine import columnar, expr as ex, plan as lp
from ndstpu.engine.columnar import (
    BOOL, DATE, FLOAT64, INT32, INT64, STRING, DType)
from ndstpu.analysis.diagnostics import Diagnostic

__all__ = ["CanonResult", "Slot", "SubtreeCanon", "canonicalize",
           "canonicalize_subtrees", "column_source"]

_CMP_OPS = {"=", "<>", "<", "<=", ">", ">="}

# functions whose trailing arguments are read host-side by the engine
# (jaxexec pulls `e.args[k].value` while building the trace) — those
# positions can never bind at runtime
_HOST_STATIC_ARGS = {"substr": 1, "substring": 1, "round": 1, "like": 1}


# ---------------------------------------------------------------------------
# schema helpers (the same table specs typecheck.py infers from)
# ---------------------------------------------------------------------------


def _schema_tables(tables):
    if tables is not None:
        return tables
    from ndstpu import analysis
    return analysis.schema_tables()


def column_source(tables: Dict[str, object]) -> Dict[str, Tuple[str, DType]]:
    """Unqualified column name -> (table, dtype).  TPC-DS column names are
    globally unique by table prefix; a name that does collide maps to
    nothing (conservative: unknown type)."""
    out: Dict[str, Tuple[str, DType]] = {}
    dead = set()
    for tname, ts in tables.items():
        for spec in ts.columns:
            if spec.name in out and out[spec.name][0] != tname:
                dead.add(spec.name)
            out.setdefault(spec.name, (tname, spec.dtype))
    for name in dead:
        out.pop(name, None)
    return out


def _fold_neg(e: ex.Expr) -> ex.Expr:
    """neg(Literal n) -> Literal(-n): the sign is part of the VALUE, not
    the structure, so `= -6` and `= 6` canonicalize to one fingerprint."""
    if isinstance(e, ex.UnaryOp) and e.op == "neg" and \
            isinstance(e.operand, ex.Literal) and \
            isinstance(e.operand.value, (int, float)) and \
            not isinstance(e.operand.value, bool):
        return ex.Literal(-e.operand.value, e.operand.ctype)
    return e


def projection_defs(plan: lp.Plan) -> Dict[str, ex.Expr]:
    """Output name -> defining expression for every projected/aggregated/
    windowed column in the plan.  Lets the classifier see through the
    optimizer's internal renames (`__pv_*` pre-projections): a compare
    against such a name resolves to the base column it carries.  Names
    are plan-wide (no scoping) — good enough for TYPING, and a wrong
    scope can only misclassify a slot, which is a perf hazard, never a
    correctness one."""
    defs: Dict[str, ex.Expr] = {}
    for node in plan.walk():
        if isinstance(node, lp.Project) or isinstance(node, lp.Window):
            pairs = node.exprs
        elif isinstance(node, lp.Aggregate):
            pairs = list(node.group_by) + list(node.aggs)
        else:
            continue
        for name, e in pairs:
            if isinstance(e, ex.ColumnRef) and \
                    e.name.split(".")[-1] == name:
                continue  # identity rename: colmap already covers it
            defs.setdefault(name, e)
    return defs


# ---------------------------------------------------------------------------
# result model
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Slot:
    """One lifted literal occurrence."""

    slot: int
    value: object                      # original python value (tuple for IN)
    ctype: DType                       # resolved parameter type
    kind: str                          # "bind" | "shape"
    code: Optional[str]                # NDS4xx for shape slots
    reason: str                        # classification detail
    column: Optional[Tuple[str, str]]  # (table, column) predicated, if any
    paths: Tuple[str, ...]             # plan paths of the occurrences
    orig_ctype: Optional[DType]        # Literal.ctype as written
    in_list: bool = False
    negated: bool = False


@dataclasses.dataclass(frozen=True)
class CanonResult:
    """Canonicalization of one optimized plan."""

    query: str
    fingerprint: str        # sha256[:16] over the slot-marked structure
    structure: str          # the raw structural string (debugging aid)
    canon_plan: object      # plan with Param/InParam at every slot
    exec_plan: object       # shape slots substituted back; safe to execute
    slots: Tuple[Slot, ...]
    values: Tuple[object, ...]
    diagnostics: Tuple[Diagnostic, ...]

    @property
    def bindable(self) -> List[Slot]:
        return [s for s in self.slots if s.kind == "bind"]

    @property
    def shape_affecting(self) -> List[Slot]:
        return [s for s in self.slots if s.kind == "shape"]

    @property
    def residual(self) -> str:
        """Stable signature of the shape-affecting slot values — the part
        of the cache key that still depends on literal content."""
        return ";".join(f"S{s.slot}={s.value!r}"
                        for s in self.shape_affecting)

    @property
    def cache_key(self) -> str:
        res = self.residual
        if not res:
            return f"c:{self.fingerprint}"
        rh = hashlib.sha256(res.encode()).hexdigest()[:12]
        return f"c:{self.fingerprint}:{rh}"

    @property
    def binding(self) -> ex.ParamBinding:
        # string binds are excluded: they reach the device only as
        # dictionary hit tables (recorded per-use in param_spec), never
        # as broadcast scalars — there is no device scalar for a string
        scalars = tuple((s.slot, s.ctype) for s in self.slots
                        if s.kind == "bind" and not s.in_list
                        and s.ctype.kind != "string")
        return ex.ParamBinding(values=self.values, scalars=scalars)


# ---------------------------------------------------------------------------
# canonicalizer
# ---------------------------------------------------------------------------


class _Canon:
    def __init__(self, tables: Dict[str, object], query: str,
                 defs: Optional[Dict[str, ex.Expr]] = None):
        self.query = query
        self.colmap = column_source(tables)
        self.defs = defs or {}
        self._deref: set = set()   # re-entrancy guard for defs lookups
        self.slots: List[dict] = []
        self.diags: List[Diagnostic] = []
        self.force_shape = 0      # >0 inside pre-resolved subquery plans
        self.limit_slots: Dict[int, int] = {}   # id(Limit node) -> slot

    # -- slot bookkeeping ----------------------------------------------------

    def _slot(self, kind: str, value, ctype: DType, path: str, *,
              code: Optional[str] = None, reason: str = "",
              column=None, orig_ctype=None, in_list=False,
              negated=False, tag: str = "") -> int:
        # One slot per literal OCCURRENCE, assigned in walk order.  Never
        # dedup by value: two distinct template parameters can render to
        # the same literal in one stream and different literals in the
        # next, and a value-sensitive slot assignment would give those
        # renderings different structures — the exact instability this
        # pass exists to remove.  Optimizer-duplicated literals simply
        # occupy several slots bound to the same value.
        idx = len(self.slots)
        self.slots.append(dict(
            slot=idx, value=value, ctype=ctype, kind=kind, code=code,
            reason=reason, column=column, paths=[path],
            orig_ctype=orig_ctype, in_list=in_list, negated=negated))
        if kind == "shape" and code is not None:
            self._diag(code, f"slot S{idx} value {value!r}: {reason}", path)
        return idx

    def _diag(self, code: str, message: str, path: str) -> None:
        d = Diagnostic(code=code, message=message, path=path,
                       query=self.query)
        if all(x.key() != d.key() for x in self.diags):
            self.diags.append(d)

    # -- typing helpers ------------------------------------------------------

    def _param_ctype(self, value, orig: Optional[DType]) -> DType:
        """Mirror of jaxexec.JEval._lit / expr.literal_column typing so a
        Param evaluates to the exact dtype the literal would have."""
        if isinstance(value, bool):
            return BOOL
        if isinstance(value, int):
            if orig is not None:
                return orig
            return INT64 if abs(value) > 2 ** 31 - 1 else INT32
        if isinstance(value, float):
            if orig is not None and orig.kind == "decimal":
                return orig
            return FLOAT64
        if isinstance(value, str):
            return STRING
        return orig or INT32

    def _static_type(self, e: ex.Expr) -> Optional[DType]:
        """Best-effort static type of an expression via the shared schema
        column map.  None = unknown (classify conservatively)."""
        if isinstance(e, ex.ColumnRef):
            base = e.name.split(".")[-1]
            hit = self.colmap.get(base)
            if hit:
                return hit[1]
            d = self.defs.get(base)
            if d is not None and base not in self._deref:
                self._deref.add(base)
                try:
                    return self._static_type(d)
                finally:
                    self._deref.discard(base)
            return None
        if isinstance(e, ex.Literal):
            if e.value is None:
                return e.ctype
            return self._param_ctype(e.value, e.ctype)
        if isinstance(e, ex.Param):
            return e.ctype
        if isinstance(e, ex.Cast):
            return e.target
        if isinstance(e, ex.Func):
            if e.name in ("upper", "lower", "trim", "substr", "substring"):
                return STRING
            if e.name in ("year", "month", "day", "length"):
                return INT32
            if e.name in ("coalesce", "nullif", "abs", "round") and e.args:
                return self._static_type(e.args[0])
            return None
        if isinstance(e, ex.BinOp) and e.op in ("+", "-", "*"):
            lt, rt = self._static_type(e.left), self._static_type(e.right)
            if lt is not None and rt is not None and \
                    lt.is_numeric and rt.is_numeric:
                return ex.common_type(lt, rt)
            if lt is not None and lt.kind == "date":
                return DATE
            if rt is not None and rt.kind == "date":
                return DATE
            return None
        if isinstance(e, ex.UnaryOp) and e.op == "neg":
            return self._static_type(e.operand)
        return None

    def _source_column(self, e: ex.Expr) -> Optional[Tuple[str, str]]:
        """First base-table column the expression reads, as (table, col)."""
        for node in e.walk():
            if isinstance(node, ex.ColumnRef):
                name = node.name.split(".")[-1]
                hit = self.colmap.get(name)
                if hit:
                    return (hit[0], name)
                d = self.defs.get(name)
                if d is not None and name not in self._deref:
                    self._deref.add(name)
                    try:
                        src = self._source_column(d)
                    finally:
                        self._deref.discard(name)
                    if src is not None:
                        return src
        return None

    # -- expression rewriting ------------------------------------------------

    def _lift(self, e: ex.Literal, path: str, *, shape_code=None,
              reason="", column=None, tag="") -> ex.Expr:
        """Lift one literal into a slot.  None literals and non-scalar
        values stay structural (a NULL needs no runtime value)."""
        v = e.value
        if v is None or not isinstance(v, (bool, int, float, str)):
            return e
        ct = self._param_ctype(v, e.ctype)
        if self.force_shape and shape_code is None:
            shape_code = "NDS402"
            reason = "literal inside a pre-resolved subquery is baked " \
                     "into the recorded size plan"
        if shape_code is None and isinstance(v, str):
            # string values outside the pdict compare/IN contexts have no
            # runtime binding mechanism (dictionaries bake into traces)
            shape_code = "NDS403"
            reason = reason or "string literal outside a dictionary " \
                               "predicate context"
        if shape_code is not None:
            idx = self._slot("shape", v, ct, path, code=shape_code,
                             reason=reason, column=column,
                             orig_ctype=e.ctype, tag=tag)
            return ex.Param(idx, ct, shape=True)
        idx = self._slot("bind", v, ct, path, reason=reason or "bindable",
                         column=column, orig_ctype=e.ctype, tag=tag)
        return ex.Param(idx, ct)

    def _expr(self, e: ex.Expr, path: str) -> ex.Expr:
        if isinstance(e, (ex.ColumnRef, ex.Star, ex.Param, ex.InParam)):
            return e
        if isinstance(e, ex.Literal):
            return self._lift(e, path)
        if isinstance(e, ex.Cast):
            # fold cast('YYYY-MM-DD' as date) into a DATE-typed slot: the
            # commonest parameterized form in the corpus
            if e.target.kind == "date" and isinstance(e.operand, ex.Literal) \
                    and isinstance(e.operand.value, str) \
                    and not self.force_shape:
                try:
                    days = columnar.parse_date_days(e.operand.value)
                except Exception:
                    days = None
                if days is not None:
                    idx = self._slot("bind", days, DATE, path,
                                     reason="date literal (cast folded)",
                                     orig_ctype=None, tag="date")
                    return ex.Param(idx, DATE)
            if isinstance(e.operand, ex.Literal) and \
                    isinstance(e.operand.value, str) and \
                    e.target.kind != "string":
                # other string-parse casts run host-side over the literal's
                # one-entry dictionary — keep concrete
                op = self._lift(e.operand, path, shape_code="NDS403",
                                reason=f"string literal under a parse cast "
                                       f"to {e.target}")
                return ex.Cast(op, e.target)
            return ex.Cast(self._expr(e.operand, path), e.target)
        if isinstance(e, ex.BinOp):
            return self._binop(e, path)
        if isinstance(e, ex.UnaryOp):
            folded = _fold_neg(e)
            if folded is not e:
                return self._expr(folded, path)
            return ex.UnaryOp(e.op, self._expr(e.operand, path))
        if isinstance(e, ex.Case):
            whens = []
            for c, v in e.whens:
                cc = self._expr(c, path)
                whens.append((cc, self._case_value(v, path)))
            dflt = self._case_value(e.default, path) \
                if e.default is not None else None
            return ex.Case(tuple(whens), dflt)
        if isinstance(e, ex.Func):
            return self._func(e, path)
        if isinstance(e, ex.InList):
            return self._in_list(e, path)
        if isinstance(e, ex.AggExpr):
            if isinstance(e.arg, ex.Star):
                return e
            return ex.AggExpr(e.func, self._expr(e.arg, path), e.distinct)
        if isinstance(e, ex.WindowExpr):
            return ex.WindowExpr(
                e.func,
                None if e.arg is None or isinstance(e.arg, ex.Star)
                else self._expr(e.arg, path),
                tuple(self._expr(x, path) for x in e.partition_by),
                tuple((self._expr(k[0], path),) + tuple(k[1:])
                      for k in e.order_by),
                e.frame)
        if isinstance(e, ex.SubqueryExpr):
            # the subquery executes once at discovery and its RESULT is
            # recorded into the replay program — any literal underneath is
            # baked into that recorded value, so lift shape-only (the
            # differing value must change the cache key)
            self.force_shape += 1
            try:
                sub = self._node(e.plan, f"{path}/subquery") \
                    if e.plan is not None else None
                oper = self._expr(e.operand, path) \
                    if e.operand is not None else None
            finally:
                self.force_shape -= 1
            return ex.SubqueryExpr(e.kind, sub, oper, e.negated,
                                   e.correlated_predicates)
        return e

    def _case_value(self, e: ex.Expr, path: str) -> ex.Expr:
        """Direct literal THEN/ELSE values keep the point bounds that the
        engine's small-domain group-by paths plan around (jaxexec._lit) —
        binding them would change compiled path selection, so they stay
        concrete as shape slots."""
        if isinstance(e, ex.Literal) and e.value is not None and \
                not isinstance(e.value, str):
            return self._lift(e, path, shape_code="NDS401",
                              reason="CASE branch value carries point "
                                     "bounds for domain planning",
                              tag="case")
        return self._expr(e, path)

    def _binop(self, e: ex.BinOp, path: str) -> ex.Expr:
        op = e.op
        if op in _CMP_OPS:
            for lit, other, swapped in ((e.left, e.right, False),
                                        (e.right, e.left, True)):
                if not (isinstance(lit, ex.Literal) and
                        isinstance(lit.value, str)):
                    continue
                ot = self._static_type(other)
                if ot is not None and ot.kind == "string" and \
                        not self.force_shape:
                    # string parameter in a dictionary compare: bound at
                    # dispatch as a host-computed hit vector over the
                    # counterpart column's dictionary
                    idx = self._slot(
                        "bind", lit.value, STRING, path,
                        reason=f"string compare ({op})",
                        column=self._source_column(other),
                        orig_ctype=lit.ctype, tag="str")
                    pnode = ex.Param(idx, STRING)
                    oc = self._expr(other, path)
                    return ex.BinOp(op, oc, pnode) if swapped \
                        else ex.BinOp(op, pnode, oc)
                if ot is not None and ot.kind == "date" and \
                        not self.force_shape:
                    # bare date-string vs a date column: both backends'
                    # implicit string->date compare coercion parses it,
                    # so bind the parsed days as a DATE slot — the same
                    # shape as the cast-folded date literal, closing the
                    # '2002-4-01'-style NDS403 cache-key residuals
                    try:
                        days = columnar.parse_date_days(lit.value)
                    except ValueError:
                        days = None
                    if days is not None:
                        idx = self._slot(
                            "bind", days, DATE, path,
                            reason="date string compare (implicit "
                                   "string->date coercion)",
                            column=self._source_column(other),
                            orig_ctype=None, tag="date")
                        pnode = ex.Param(idx, DATE)
                        oc = self._expr(other, path)
                        return ex.BinOp(op, oc, pnode) if swapped \
                            else ex.BinOp(op, pnode, oc)
            # date +/- int literal lives below; comparisons recurse with
            # source-column attribution for the binding report
            left = self._cmp_side(e.left, e.right, path)
            right = self._cmp_side(e.right, e.left, path)
            return ex.BinOp(op, left, right)
        if op in ("+", "-"):
            for lit, other in ((e.left, e.right), (e.right, e.left)):
                ot = self._static_type(other)
                if isinstance(lit, ex.Literal) and \
                        isinstance(lit.value, int) and \
                        not isinstance(lit.value, bool) and \
                        ot is not None and ot.kind == "date":
                    # interval width: feeds date-range capacity planning
                    lc = self._lift(
                        lit, path, shape_code="NDS401",
                        reason="interval width in date arithmetic "
                               "changes padded capacities",
                        column=self._source_column(other), tag="interval")
                    oc = self._expr(other, path)
                    return ex.BinOp(op, lc, oc) if lit is e.left \
                        else ex.BinOp(op, oc, lc)
        return ex.BinOp(op, self._expr(e.left, path),
                        self._expr(e.right, path))

    def _cmp_side(self, side: ex.Expr, other: ex.Expr,
                  path: str) -> ex.Expr:
        side = _fold_neg(side)
        if isinstance(side, ex.Literal):
            return self._lift(side, path,
                              column=self._source_column(other))
        if isinstance(side, ex.Cast) and side.target.kind == "date" \
                and isinstance(side.operand, ex.Literal) \
                and isinstance(side.operand.value, str) \
                and not self.force_shape:
            # folded date literal in a comparison: attribute the slot to
            # the column it predicates (the param_audit binding report)
            try:
                days = columnar.parse_date_days(side.operand.value)
            except Exception:
                days = None
            if days is not None:
                idx = self._slot("bind", days, DATE, path,
                                 reason="date literal (cast folded)",
                                 column=self._source_column(other),
                                 orig_ctype=None, tag="date")
                return ex.Param(idx, DATE)
        return self._expr(side, path)

    def _func(self, e: ex.Func, path: str) -> ex.Expr:
        if e.name == "grouping":
            return e  # resolved statically per grouping set
        if e.name == "coalesce":
            # coalesce_common_type() inspects Literal nodes to keep exact
            # decimal typing (the q75 drift fix) — literal args must
            # survive as literals
            args = []
            for a in e.args:
                if isinstance(a, ex.Literal):
                    args.append(self._lift(
                        a, path, shape_code="NDS403",
                        reason="coalesce argument participates in exact "
                               "literal typing"))
                else:
                    args.append(self._expr(a, path))
            return ex.Func(e.name, tuple(args))
        host = _HOST_STATIC_ARGS.get(e.name)
        args = []
        for i, a in enumerate(e.args):
            if host is not None and i >= host and \
                    isinstance(a, ex.Literal):
                args.append(self._lift(
                    a, path, shape_code="NDS403",
                    reason=f"{e.name}() argument {i} is read host-side "
                           "while building the trace",
                    column=self._source_column(e.args[0])))
            else:
                args.append(self._expr(a, path))
        return ex.Func(e.name, tuple(args))

    def _in_list(self, e: ex.InList, path: str) -> ex.Expr:
        operand = self._expr(e.operand, path)
        vals = tuple(e.values)
        if not vals or any(v is None for v in vals) or self.force_shape:
            return ex.InList(operand, vals, e.negated)
        ot = self._static_type(e.operand)
        col = self._source_column(e.operand)
        if ot is not None and ot.kind == "string" and \
                all(isinstance(v, str) for v in vals):
            idx = self._slot("bind", vals, STRING, path,
                             reason="string IN-list (dictionary membership)",
                             column=col, in_list=True, negated=e.negated,
                             tag="in")
            return ex.InParam(operand, idx, len(vals), e.negated)
        if ot is not None and (ot.is_numeric or ot.kind == "date"):
            coerced, had_null = ex.coerce_in_values(ot, vals)
            if not had_null and len(coerced) == len(vals):
                idx = self._slot("bind", vals, ot, path,
                                 reason=f"IN-list over {ot} operand",
                                 column=col, in_list=True,
                                 negated=e.negated, tag="in")
                return ex.InParam(operand, idx, len(vals), e.negated)
            self._diag("NDS403", f"IN-list values {vals!r} do not coerce "
                                 f"cleanly to {ot}; kept literal", path)
            return ex.InList(operand, vals, e.negated)
        self._diag("NDS403", "IN-list operand type unresolved; values "
                             "kept literal", path)
        return ex.InList(operand, vals, e.negated)

    # -- plan rewriting ------------------------------------------------------

    def _node(self, p: lp.Plan, path: str) -> lp.Plan:
        t = type(p).__name__

        def child(c, i=0):
            return self._node(c, f"{path}/{type(c).__name__}[{i}]")

        if isinstance(p, lp.Scan):
            pred = self._expr(p.predicate, path) \
                if p.predicate is not None else None
            return lp.Scan(p.table, p.alias,
                           None if p.columns is None else list(p.columns),
                           pred)
        if isinstance(p, lp.InlineTable):
            return lp.InlineTable(p.table, p.name)
        if isinstance(p, lp.Filter):
            return lp.Filter(child(p.child), self._expr(p.condition, path))
        if isinstance(p, lp.Project):
            return lp.Project(child(p.child),
                              [(n, self._expr(e, path)) for n, e in p.exprs])
        if isinstance(p, lp.Join):
            keys = []
            for le, re_ in p.keys:
                keys.append((self._join_key(le, path),
                             self._join_key(re_, path)))
            extra = self._expr(p.extra, path) if p.extra is not None else None
            return lp.Join(child(p.left, 0),
                           self._node(p.right,
                                      f"{path}/{type(p.right).__name__}[1]"),
                           p.kind, keys, extra, p.mark)
        if isinstance(p, lp.Aggregate):
            gb = [(n, self._group_key(e, path)) for n, e in p.group_by]
            aggs = [(n, self._expr(e, path)) for n, e in p.aggs]
            return lp.Aggregate(child(p.child), gb, aggs,
                                None if p.grouping_sets is None
                                else [list(s) for s in p.grouping_sets])
        if isinstance(p, lp.Window):
            return lp.Window(child(p.child),
                             [(n, self._expr(e, path)) for n, e in p.exprs])
        if isinstance(p, lp.Sort):
            # keys are (expr, asc) or (expr, asc, nulls_first)
            return lp.Sort(child(p.child),
                           [(self._expr(k[0], path),) + tuple(k[1:])
                            for k in p.keys])
        if isinstance(p, lp.Limit):
            node = lp.Limit(child(p.child), p.n)
            if not self.force_shape:
                idx = self._slot("shape", p.n, INT32, path, code="NDS401",
                                 reason="LIMIT row count is a static "
                                        "output shape", tag="limit")
                self.limit_slots[id(node)] = idx
            return node
        if isinstance(p, lp.Distinct):
            return lp.Distinct(child(p.child))
        if isinstance(p, lp.SetOp):
            return lp.SetOp(p.kind, child(p.left, 0),
                            self._node(p.right,
                                       f"{path}/{type(p.right).__name__}[1]"),
                            p.all)
        if isinstance(p, lp.SubqueryAlias):
            return lp.SubqueryAlias(child(p.child), p.alias,
                                    None if p.column_aliases is None
                                    else list(p.column_aliases))
        if isinstance(p, lp.DeviceResult):
            return p
        raise TypeError(f"canonicalize: unknown plan node {t}")

    def _join_key(self, e: ex.Expr, path: str) -> ex.Expr:
        if isinstance(e, ex.Literal) and e.value is not None:
            # join machinery plans radix/LUT layout from key bounds —
            # a literal key's point bounds must survive
            return self._lift(e, path, shape_code="NDS401",
                              reason="literal join key feeds radix "
                                     "planning bounds", tag="joinkey")
        return self._expr(e, path)

    def _group_key(self, e: ex.Expr, path: str) -> ex.Expr:
        if isinstance(e, ex.Literal) and e.value is not None:
            return self._lift(e, path, shape_code="NDS401",
                              reason="literal group key bounds the "
                                     "group-by domain", tag="groupkey")
        return self._expr(e, path)


# ---------------------------------------------------------------------------
# fingerprint (jax-free twin of jaxexec._plan_fp with slot markers)
# ---------------------------------------------------------------------------


def _inline_table_fp(t) -> str:
    parts = []
    for name in t.column_names:
        c = t.columns[name]
        data = np.ascontiguousarray(np.asarray(c.data))
        crc = zlib.crc32(data.tobytes())
        if c.valid is not None:
            crc = zlib.crc32(np.ascontiguousarray(c.valid).tobytes(), crc)
        if c.dictionary is not None:
            crc = zlib.crc32(str(len(c.dictionary)).encode(), crc)
            for s in c.dictionary:
                b = str(s).encode()
                crc = zlib.crc32(f"{len(b)}:".encode() + b, crc)
        parts.append(f"{name}:{c.ctype!r}:{data.dtype}{data.shape}:{crc}")
    return f"T({t.num_rows};" + ";".join(parts) + ")"


def _structure(o, limit_slots: Dict[int, int], out: List[str]) -> None:
    if isinstance(o, lp.InlineTable):
        out.append(f"IT{_inline_table_fp(o.table)}")
    elif isinstance(o, lp.Limit) and id(o) in limit_slots:
        out.append(f"Limit(S{limit_slots[id(o)]},")
        _structure(o.child, limit_slots, out)
        out.append(")")
    elif isinstance(o, ex.Param):
        # slot marker only: the VALUE lives in the binding (bindable) or
        # the residual signature (shape) — never in the structure
        k = "S" if o.shape else "P"
        out.append(f"{k}{o.slot}:{o.ctype!r}")
    elif isinstance(o, ex.InParam):
        neg = "!" if o.negated else ""
        out.append(f"IN{neg}(P{o.slot}[{o.n}],")
        _structure(o.operand, limit_slots, out)
        out.append(")")
    elif dataclasses.is_dataclass(o) and not isinstance(o, type):
        out.append(type(o).__name__)
        out.append("(")
        for f in dataclasses.fields(o):
            _structure(getattr(o, f.name), limit_slots, out)
            out.append(",")
        out.append(")")
    elif isinstance(o, (list, tuple)):
        out.append("[")
        for x in o:
            _structure(x, limit_slots, out)
            out.append(",")
        out.append("]")
    elif isinstance(o, np.ndarray):
        out.append(f"ND{o.dtype}{o.shape}{zlib.crc32(o.tobytes())}")
    else:
        out.append(repr(o))


# ---------------------------------------------------------------------------
# exec-plan substitution (shape slots back to literals)
# ---------------------------------------------------------------------------


def _substitute_expr(e: ex.Expr, slots: List[dict]) -> ex.Expr:
    if isinstance(e, ex.Param):
        if not e.shape:
            return e
        s = slots[e.slot]
        return ex.Literal(s["value"], s["orig_ctype"])
    if isinstance(e, ex.InParam):
        return ex.InParam(_substitute_expr(e.operand, slots), e.slot,
                          e.n, e.negated)
    if isinstance(e, ex.Literal) or isinstance(
            e, (ex.ColumnRef, ex.Star)):
        return e
    if isinstance(e, ex.Cast):
        return ex.Cast(_substitute_expr(e.operand, slots), e.target)
    if isinstance(e, ex.BinOp):
        return ex.BinOp(e.op, _substitute_expr(e.left, slots),
                        _substitute_expr(e.right, slots))
    if isinstance(e, ex.UnaryOp):
        return ex.UnaryOp(e.op, _substitute_expr(e.operand, slots))
    if isinstance(e, ex.Case):
        return ex.Case(
            tuple((_substitute_expr(c, slots), _substitute_expr(v, slots))
                  for c, v in e.whens),
            _substitute_expr(e.default, slots)
            if e.default is not None else None)
    if isinstance(e, ex.Func):
        return ex.Func(e.name, tuple(_substitute_expr(a, slots)
                                     for a in e.args))
    if isinstance(e, ex.InList):
        return ex.InList(_substitute_expr(e.operand, slots), e.values,
                         e.negated)
    if isinstance(e, ex.AggExpr):
        if isinstance(e.arg, ex.Star):
            return e
        return ex.AggExpr(e.func, _substitute_expr(e.arg, slots),
                          e.distinct)
    if isinstance(e, ex.WindowExpr):
        return ex.WindowExpr(
            e.func,
            None if e.arg is None or isinstance(e.arg, ex.Star)
            else _substitute_expr(e.arg, slots),
            tuple(_substitute_expr(x, slots) for x in e.partition_by),
            tuple((_substitute_expr(k[0], slots),) + tuple(k[1:])
                  for k in e.order_by),
            e.frame)
    if isinstance(e, ex.SubqueryExpr):
        return ex.SubqueryExpr(
            e.kind,
            _substitute_plan(e.plan, slots) if e.plan is not None else None,
            _substitute_expr(e.operand, slots)
            if e.operand is not None else None,
            e.negated, e.correlated_predicates)
    return e


def _substitute_plan(p: lp.Plan, slots: List[dict]) -> lp.Plan:
    sub = lambda e: _substitute_expr(e, slots)  # noqa: E731
    if isinstance(p, lp.Scan):
        return lp.Scan(p.table, p.alias,
                       None if p.columns is None else list(p.columns),
                       sub(p.predicate) if p.predicate is not None else None)
    if isinstance(p, lp.InlineTable):
        return lp.InlineTable(p.table, p.name)
    if isinstance(p, lp.Filter):
        return lp.Filter(_substitute_plan(p.child, slots), sub(p.condition))
    if isinstance(p, lp.Project):
        return lp.Project(_substitute_plan(p.child, slots),
                          [(n, sub(e)) for n, e in p.exprs])
    if isinstance(p, lp.Join):
        return lp.Join(_substitute_plan(p.left, slots),
                       _substitute_plan(p.right, slots), p.kind,
                       [(sub(a), sub(b)) for a, b in p.keys],
                       sub(p.extra) if p.extra is not None else None,
                       p.mark)
    if isinstance(p, lp.Aggregate):
        return lp.Aggregate(_substitute_plan(p.child, slots),
                            [(n, sub(e)) for n, e in p.group_by],
                            [(n, sub(e)) for n, e in p.aggs],
                            None if p.grouping_sets is None
                            else [list(s) for s in p.grouping_sets])
    if isinstance(p, lp.Window):
        return lp.Window(_substitute_plan(p.child, slots),
                         [(n, sub(e)) for n, e in p.exprs])
    if isinstance(p, lp.Sort):
        return lp.Sort(_substitute_plan(p.child, slots),
                       [(sub(k[0]),) + tuple(k[1:]) for k in p.keys])
    if isinstance(p, lp.Limit):
        return lp.Limit(_substitute_plan(p.child, slots), p.n)
    if isinstance(p, lp.Distinct):
        return lp.Distinct(_substitute_plan(p.child, slots))
    if isinstance(p, lp.SetOp):
        return lp.SetOp(p.kind, _substitute_plan(p.left, slots),
                        _substitute_plan(p.right, slots), p.all)
    if isinstance(p, lp.SubqueryAlias):
        return lp.SubqueryAlias(_substitute_plan(p.child, slots), p.alias,
                                None if p.column_aliases is None
                                else list(p.column_aliases))
    if isinstance(p, lp.DeviceResult):
        return p
    raise TypeError(f"substitute: unknown plan node {type(p).__name__}")


# the optimizer's fused-sibling rewrite names its internal bucket/agg
# columns __ssa<md5-of-conjuncts> (optimizer._build_fused) — a hash OVER
# LITERAL VALUES, so two renderings of one template get different
# internal names for the same structure.  The names never escape the
# plan (the final projection uses template aliases), so renumber them by
# first occurrence before fingerprinting.
_GENERATED_NAME = re.compile(r"__ssa[0-9a-f]{8}x*")


def _normalize_generated_names(structure: str) -> str:
    seen: Dict[str, str] = {}

    def sub(m: "re.Match") -> str:
        return seen.setdefault(m.group(0), f"__ssa{len(seen)}")

    return _GENERATED_NAME.sub(sub, structure)


# ---------------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------------


def canonicalize(plan: lp.Plan, tables: Optional[Dict[str, object]] = None,
                 query: str = "") -> CanonResult:
    """Canonicalize an OPTIMIZED logical plan.

    Returns the canonical plan (every lifted literal a Param slot), the
    executable plan (shape slots substituted back), the structural
    fingerprint, the slot binding list, and NDS4xx diagnostics for the
    shape-affecting residue."""
    c = _Canon(_schema_tables(tables), query, defs=projection_defs(plan))
    canon_plan = c._node(plan, type(plan).__name__)
    out: List[str] = []
    _structure(canon_plan, c.limit_slots, out)
    structure = _normalize_generated_names("".join(out))
    fp = hashlib.sha256(structure.encode()).hexdigest()[:16]
    exec_plan = _substitute_plan(canon_plan, c.slots)
    slots = tuple(Slot(slot=s["slot"], value=s["value"], ctype=s["ctype"],
                       kind=s["kind"], code=s["code"], reason=s["reason"],
                       column=s["column"], paths=tuple(s["paths"]),
                       orig_ctype=s["orig_ctype"], in_list=s["in_list"],
                       negated=s["negated"])
                  for s in c.slots)
    return CanonResult(
        query=query, fingerprint=fp, structure=structure,
        canon_plan=canon_plan, exec_plan=exec_plan, slots=slots,
        values=tuple(s["value"] for s in c.slots),
        diagnostics=tuple(c.diags))


# ---------------------------------------------------------------------------
# subtree canonicalization
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class SubtreeCanon:
    """Canonicalization of one plan SUBTREE treated as its own root.

    Slot numbering restarts per subtree, so a spine shared by two queries
    collapses to one fingerprint even when the enclosing plans lift a
    different number of literals before reaching it."""

    path: str                      # canon-convention path from the plan root
    node: lp.Plan = dataclasses.field(compare=False, hash=False)
    kind: str = ""                 # root node type name
    size: int = 0                  # plan nodes in the subtree
    canon: Optional[CanonResult] = dataclasses.field(
        default=None, compare=False, hash=False)


def _plan_children(p: lp.Plan) -> List[lp.Plan]:
    """Plan-node children in the ordinal order `_Canon._node` paths use."""
    if isinstance(p, (lp.Join, lp.SetOp)):
        return [p.left, p.right]
    child = getattr(p, "child", None)
    return [child] if isinstance(child, lp.Plan) else []


def _subtree_size(p: lp.Plan) -> int:
    return 1 + sum(_subtree_size(c) for c in _plan_children(p))


def canonicalize_subtrees(plan: lp.Plan,
                          tables: Optional[Dict[str, object]] = None,
                          query: str = "") -> List[SubtreeCanon]:
    """Canonicalize EVERY plan subtree as its own root, root-first.

    Paths follow the `_Canon._node` convention
    (``RootType/ChildType[i]/...``) so subtree records line up with the
    NDS diagnostics anchored on the same plan.  A subtree whose
    canonicalization raises is recorded with ``canon=None`` rather than
    aborting the sweep — the callers (spines.py, session splicing) treat
    it as opaque/unshareable."""
    tables = _schema_tables(tables)
    out: List[SubtreeCanon] = []

    def visit(p: lp.Plan, path: str) -> None:
        try:
            c = canonicalize(p, tables, query)
        except Exception:
            c = None
        out.append(SubtreeCanon(
            path=path, node=p, kind=type(p).__name__,
            size=_subtree_size(p), canon=c))
        for i, ch in enumerate(_plan_children(p)):
            visit(ch, f"{path}/{type(ch).__name__}[{i}]")

    visit(plan, type(plan).__name__)
    return out
