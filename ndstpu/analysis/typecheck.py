"""Bottom-up schema/type inference over the logical plan IR.

Static twin of the two evaluators: for every operator in
``engine/plan.py`` and every expression in ``engine/expr.py`` it derives
the output schema — column name, dtype (kind + decimal precision/scale),
nullability — **without touching data**, mirroring the numpy
``expr.Evaluator`` / jax ``jaxexec.JEval`` result-type rules exactly
(``/`` is always float64 and NULL on zero, decimal ``*`` adds scales at
precision 38, CASE unifies numerics via ``common_type``, COALESCE uses
the shared ``coalesce_common_type``, date ± int stays date, ...).

On top of inference it emits NDS1xx typing diagnostics
(analysis/diagnostics.py): join-key dtype mismatches, lossy casts,
int32-aggregate overflow advisories at a given scale factor, SetOp
arity/type drift, and under-specified sort keys ahead of a LIMIT.

Import-hygienic: numpy only (via engine.columnar) — never jax.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from ndstpu.engine import expr as ex
from ndstpu.engine import plan as lp
from ndstpu.engine.columnar import (
    BOOL,
    DATE,
    FLOAT64,
    INT32,
    INT64,
    STRING,
    DType,
    decimal,
)
from ndstpu.analysis.diagnostics import Diagnostic

#: row count of the largest SF1 fact table (store_sales ≈ 2.88M rows);
#: the NDS103 overflow advisory scales it linearly with the scale factor.
_SF1_MAX_FACT_ROWS = 2_880_000


@dataclasses.dataclass(frozen=True)
class ColType:
    """Inferred column type; ``ctype is None`` means statically unknown
    (DeviceResult subtrees, unresolved names) — unknown types propagate
    silently and never produce diagnostics."""

    ctype: Optional[DType]
    nullable: bool = True

    @property
    def known(self) -> bool:
        return self.ctype is not None

    @property
    def kind(self) -> Optional[str]:
        return self.ctype.kind if self.ctype is not None else None


UNKNOWN = ColType(None, True)


class Schema:
    """Ordered (name, ColType) list; ``cols=None`` = wholly unknown."""

    def __init__(self, cols: Optional[List[Tuple[str, ColType]]]):
        self.cols = cols

    @property
    def known(self) -> bool:
        return self.cols is not None

    @property
    def names(self) -> List[str]:
        return [n for n, _ in self.cols] if self.known else []

    def get(self, name: str) -> ColType:
        if not self.known:
            return UNKNOWN
        for n, t in self.cols:
            if n == name:
                return t
        return UNKNOWN

    def __repr__(self):
        if not self.known:
            return "Schema(?)"
        return "Schema(" + ", ".join(
            f"{n}:{t.kind or '?'}{'?' if t.nullable else ''}"
            for n, t in self.cols) + ")"


def _child_path(path: str, child: lp.Plan, i: int) -> str:
    return f"{path}/{type(child).__name__}[{i}]"


class TypeChecker:
    """One pass per query part; collects diagnostics in ``self.diags``."""

    def __init__(self, tables: Dict[str, object], query: str = "",
                 scale_factor: Optional[float] = None):
        # tables: name -> ndstpu.schema.TableSchema (ColumnSpec columns)
        self.tables = tables
        self.query = query
        self.scale_factor = scale_factor
        self.diags: List[Diagnostic] = []

    def _emit(self, code: str, message: str, path: str) -> None:
        self.diags.append(Diagnostic(code=code, message=message, path=path,
                                     query=self.query))

    # -- plan nodes ----------------------------------------------------------

    def infer(self, p: lp.Plan, path: str = "") -> Schema:
        path = path or type(p).__name__
        meth = getattr(self, "_infer_" + type(p).__name__.lower(), None)
        if meth is None:
            return Schema(None)
        saved = getattr(self, "_path", "")
        self._path = path
        try:
            return meth(p, path)
        finally:
            self._path = saved

    def _children(self, p: lp.Plan, path: str) -> List[Schema]:
        return [self.infer(c, _child_path(path, c, i))
                for i, c in enumerate(p.children())]

    def _infer_scan(self, p: lp.Scan, path: str) -> Schema:
        ts = self.tables.get(p.table)
        if ts is None:
            return Schema(None)
        names = p.columns if p.columns is not None else \
            [c.name for c in ts.columns]
        specs = {c.name: c for c in ts.columns}
        cols = []
        for n in names:
            spec = specs.get(n)
            cols.append((n, ColType(spec.dtype, spec.nullable)
                         if spec is not None else UNKNOWN))
        return Schema(cols)

    def _infer_inlinetable(self, p: lp.InlineTable, path: str) -> Schema:
        t = p.table
        try:
            return Schema([
                (n, ColType(t.column(n).ctype,
                            t.column(n).valid is not None))
                for n in t.column_names])
        except Exception:
            return Schema(None)

    def _infer_filter(self, p: lp.Filter, path: str) -> Schema:
        child, = self._children(p, path)
        self.expr_type(p.condition, child)
        return child

    def _infer_project(self, p: lp.Project, path: str) -> Schema:
        child, = self._children(p, path)
        return Schema([(n, self.expr_type(e, child)) for n, e in p.exprs])

    def _infer_subqueryalias(self, p: lp.SubqueryAlias,
                             path: str) -> Schema:
        child, = self._children(p, path)
        if p.column_aliases is not None and child.known:
            return Schema([(a, t) for a, (_, t)
                           in zip(p.column_aliases, child.cols)])
        return child

    def _infer_limit(self, p: lp.Limit, path: str) -> Schema:
        child, = self._children(p, path)
        if isinstance(p.child, lp.Sort) and child.known and \
                len(p.child.keys) < len(child.cols):
            # ties among equal sort keys make which rows survive the
            # LIMIT backend-dependent (CPU-vs-TPU validation hazard)
            self._emit(
                "NDS105",
                f"LIMIT {p.n} above a sort on {len(p.child.keys)} of "
                f"{len(child.cols)} output columns: ties are broken "
                "nondeterministically", path)
        return child

    def _infer_distinct(self, p: lp.Distinct, path: str) -> Schema:
        return self._children(p, path)[0]

    def _infer_sort(self, p: lp.Sort, path: str) -> Schema:
        child, = self._children(p, path)
        for entry in p.keys:
            self.expr_type(entry[0], child)
        return child

    def _infer_deviceresult(self, p: lp.DeviceResult, path: str) -> Schema:
        return Schema(None)

    def _infer_setop(self, p: lp.SetOp, path: str) -> Schema:
        left, right = self._children(p, path)
        if not (left.known and right.known):
            return Schema(None)
        if len(left.cols) != len(right.cols):
            self._emit("NDS104",
                       f"{p.kind} arity mismatch: {len(left.cols)} vs "
                       f"{len(right.cols)} columns", path)
            return left
        out = []
        for (n, lt), (rn, rt) in zip(left.cols, right.cols):
            nullable = lt.nullable or rt.nullable
            if not (lt.known and rt.known):
                out.append((n, ColType(None, nullable)))
                continue
            if ex.is_numeric(lt.ctype) and ex.is_numeric(rt.ctype):
                ct = ex.common_type(lt.ctype, rt.ctype)
            elif lt.kind == rt.kind:
                ct = lt.ctype
            else:
                self._emit("NDS104",
                           f"{p.kind} column {n!r}: {lt.kind} vs "
                           f"{rt.kind} ({rn!r})", path)
                ct = lt.ctype
            out.append((n, ColType(ct, nullable)))
        return Schema(out)

    def _infer_join(self, p: lp.Join, path: str) -> Schema:
        left, right = self._children(p, path)
        for i, (le, re_) in enumerate(p.keys):
            lt = self.expr_type(le, left)
            rt = self.expr_type(re_, right)
            if lt.known and rt.known and lt.kind != rt.kind and not (
                    ex.is_numeric(lt.ctype) and ex.is_numeric(rt.ctype)):
                self._emit("NDS101",
                           f"join key {i}: {lt.kind} vs {rt.kind} "
                           f"({le} = {re_})", f"{path}/keys[{i}]")
        if p.extra is not None:
            merged = Schema(
                (left.cols or []) + (right.cols or [])
                if left.known and right.known else None)
            self.expr_type(p.extra, merged)
        kind = p.kind
        if not (left.known and right.known):
            if kind in ("semi", "anti", "nullaware_anti", "mark") \
                    and left.known:
                pass  # right side unknown is fine for left-only outputs
            else:
                return Schema(None)
        if kind in ("semi", "anti", "nullaware_anti"):
            return left
        if kind == "mark":
            return Schema(list(left.cols) +
                          [(p.mark, ColType(BOOL, False))])
        lnull = kind in ("right", "full")
        rnull = kind in ("left", "full")
        lcols = [(n, ColType(t.ctype, t.nullable or lnull))
                 for n, t in left.cols]
        rcols = [(n, ColType(t.ctype, t.nullable or rnull))
                 for n, t in right.cols]
        return Schema(lcols + rcols)

    def _infer_aggregate(self, p: lp.Aggregate, path: str) -> Schema:
        child, = self._children(p, path)
        out = []
        for name, e in p.group_by:
            t = self.expr_type(e, child)
            if p.grouping_sets is not None:
                # rollup rows carry NULL for the excluded keys
                t = ColType(t.ctype, True)
            out.append((name, t))
        for name, e in p.aggs:
            out.append((name, self.expr_type(e, child)))
            self._check_int32_overflow(e, child, path)
        return Schema(out)

    def _infer_window(self, p: lp.Window, path: str) -> Schema:
        child, = self._children(p, path)
        if not child.known:
            return Schema(None)
        return Schema(list(child.cols) +
                      [(n, self.expr_type(e, child)) for n, e in p.exprs])

    def _check_int32_overflow(self, e: ex.Expr, schema: Schema,
                              path: str) -> None:
        """NDS103: sum over an int32 column can exceed int64 once the
        (linearly scaled) fact row estimate crosses 2^32 rows — advisory
        only, keyed to the caller-supplied scale factor."""
        if self.scale_factor is None:
            return
        rows = self.scale_factor * _SF1_MAX_FACT_ROWS
        if rows < 2 ** 32:
            return
        for sub in e.walk():
            if isinstance(sub, ex.AggExpr) and sub.func == "sum" and \
                    not isinstance(sub.arg, ex.Star):
                at = self.expr_type(sub.arg, schema)
                if at.kind == "int32":
                    self._emit(
                        "NDS103",
                        f"sum({sub.arg}) over int32 at SF "
                        f"{self.scale_factor:g}: ~{rows:.2g} rows can "
                        "overflow the int64 accumulator", path)

    # -- expressions ---------------------------------------------------------

    def agg_result(self, func: str, arg_t: ColType,
                   is_star: bool) -> ColType:
        """Result type of one aggregate call (mirrors jaxexec._agg_column
        and physical's aggregate path)."""
        if func == "count":
            return ColType(INT64, False)
        if func == "sum":
            if is_star or not arg_t.known:
                return UNKNOWN
            k = arg_t.kind
            if k == "decimal":
                return ColType(decimal(38, arg_t.ctype.scale), True)
            if k in ("int32", "int64", "bool"):
                return ColType(INT64, True)
            return ColType(FLOAT64, True)
        if func == "avg":
            return ColType(FLOAT64, True)
        if func in ("min", "max"):
            return ColType(arg_t.ctype, True)
        if func in ("stddev_samp", "var_samp", "stddev", "variance"):
            return ColType(FLOAT64, True)
        return UNKNOWN

    def expr_type(self, e: ex.Expr, schema: Schema) -> ColType:
        if isinstance(e, ex.ColumnRef):
            return schema.get(e.name)
        if isinstance(e, ex.Literal):
            return self._literal_type(e)
        if isinstance(e, ex.Star):
            return UNKNOWN
        if isinstance(e, ex.Cast):
            return self._cast_type(e, schema)
        if isinstance(e, ex.BinOp):
            return self._binop_type(e, schema)
        if isinstance(e, ex.UnaryOp):
            t = self.expr_type(e.operand, schema)
            if e.op == "not":
                return ColType(BOOL, t.nullable)
            if e.op == "neg":
                return t
            return ColType(BOOL, False)  # isnull / isnotnull
        if isinstance(e, ex.Case):
            return self._case_type(e, schema)
        if isinstance(e, ex.Func):
            return self._func_type(e, schema)
        if isinstance(e, ex.InList):
            t = self.expr_type(e.operand, schema)
            return ColType(BOOL, t.nullable)
        if isinstance(e, ex.Param):
            # lifted literal (analysis/canon.py): typed like the literal
            # it replaced — parameters are never NULL (None is not lifted)
            return ColType(e.ctype, False)
        if isinstance(e, ex.InParam):
            t = self.expr_type(e.operand, schema)
            return ColType(BOOL, t.nullable)
        if isinstance(e, ex.AggExpr):
            arg_t = UNKNOWN if isinstance(e.arg, ex.Star) else \
                self.expr_type(e.arg, schema)
            return self.agg_result(e.func, arg_t,
                                   isinstance(e.arg, ex.Star))
        if isinstance(e, ex.WindowExpr):
            if e.func in ("rank", "dense_rank", "row_number"):
                return ColType(INT64, False)
            arg_t = UNKNOWN if e.arg is None or isinstance(e.arg, ex.Star) \
                else self.expr_type(e.arg, schema)
            return self.agg_result(e.func, arg_t,
                                   e.arg is None or
                                   isinstance(e.arg, ex.Star))
        if isinstance(e, ex.SubqueryExpr):
            if e.kind in ("in", "exists"):
                return ColType(BOOL, True)
            sub = TypeChecker(self.tables, self.query, self.scale_factor)
            s = sub.infer(e.plan)
            if s.known and s.cols:
                return ColType(s.cols[0][1].ctype, True)
            return UNKNOWN
        return UNKNOWN

    def _literal_type(self, e: ex.Literal) -> ColType:
        v = e.value
        if v is None:
            return ColType(e.ctype or INT32, True)
        if isinstance(v, bool):
            return ColType(BOOL, False)
        if isinstance(v, int):
            ct = e.ctype or (INT64 if abs(v) > 2 ** 31 - 1 else INT32)
            return ColType(ct, False)
        if isinstance(v, float):
            if e.ctype is not None and e.ctype.kind == "decimal":
                return ColType(e.ctype, False)
            return ColType(FLOAT64, False)
        if isinstance(v, str):
            return ColType(STRING, False)
        return UNKNOWN

    def _cast_type(self, e: ex.Cast, schema: Schema) -> ColType:
        src = self.expr_type(e.operand, schema)
        tgt = e.target
        if not src.known:
            return ColType(tgt, True)
        k, tk = src.kind, tgt.kind
        nullable = src.nullable
        lossy = None
        if k == "decimal" and tk == "decimal":
            if tgt.scale < src.ctype.scale:
                lossy = "decimal scale narrowed " \
                    f"{src.ctype.scale}->{tgt.scale} (rounds)"
            elif (tgt.precision - tgt.scale) < \
                    (src.ctype.precision - src.ctype.scale):
                lossy = "decimal integer digits narrowed " \
                    f"({src.ctype.precision},{src.ctype.scale})->" \
                    f"({tgt.precision},{tgt.scale}) (overflow -> NULL)"
                nullable = True
        elif k == "decimal" and tk in ("int32", "int64"):
            if src.ctype.scale > 0:
                lossy = f"decimal(.,{src.ctype.scale}) -> {tk} truncates"
        elif k == "float64" and tk in ("int32", "int64", "decimal"):
            lossy = f"float64 -> {tk} loses fraction"
        elif k == "int64" and tk == "int32":
            lossy = "int64 -> int32 may wrap"
        elif k == "string" and tk != "string":
            # parse cast: unparseable strings become NULL, not lossy
            nullable = True
        if lossy is not None:
            self._emit("NDS102",
                       f"lossy cast {k} -> {tgt} in {e}: {lossy}",
                       getattr(self, "_path", "expr"))
        return ColType(tgt, nullable)

    def _binop_type(self, e: ex.BinOp, schema: Schema) -> ColType:
        lt = self.expr_type(e.left, schema)
        rt = self.expr_type(e.right, schema)
        nullable = lt.nullable or rt.nullable
        op = e.op
        if op in ("and", "or"):
            return ColType(BOOL, nullable)
        if op in ("=", "<>", "<", "<=", ">", ">="):
            return ColType(BOOL, nullable)
        if op == "||":
            return ColType(STRING, nullable)
        # arithmetic (mirrors Evaluator._arith)
        if op == "/":
            return ColType(FLOAT64, True)  # x/0 -> NULL
        if not (lt.known and rt.known):
            return ColType(None, nullable)
        lk, rk = lt.kind, rt.kind
        if lk == "date" and rk in ("int32", "int64"):
            return ColType(DATE, nullable)
        if "decimal" in (lk, rk):
            if "float64" in (lk, rk):
                return ColType(FLOAT64, nullable)
            ls = lt.ctype.scale if lk == "decimal" else 0
            rs = rt.ctype.scale if rk == "decimal" else 0
            if op == "*":
                return ColType(decimal(38, ls + rs), nullable)
            s = max(ls, rs)
            return ColType(decimal(38, s),
                           True if op == "%" else nullable)
        tgt = ex.common_type(lt.ctype, rt.ctype)
        return ColType(tgt, True if op == "%" else nullable)

    def _case_type(self, e: ex.Case, schema: Schema) -> ColType:
        cands = [self.expr_type(v, schema) for _, v in e.whens]
        if e.default is not None:
            cands.append(self.expr_type(e.default, schema))
        if any(not c.known for c in cands):
            return UNKNOWN
        tgt = cands[0].ctype
        for c in cands[1:]:
            if ex.is_numeric(c.ctype) and ex.is_numeric(tgt):
                tgt = ex.common_type(tgt, c.ctype)
            elif c.ctype.kind != tgt.kind:
                tgt = c.ctype if tgt.kind == "int32" else tgt
        nullable = e.default is None or any(c.nullable for c in cands)
        return ColType(tgt, nullable)

    def _func_type(self, e: ex.Func, schema: Schema) -> ColType:
        name = e.name
        args = [self.expr_type(a, schema) for a in e.args]
        any_null = any(a.nullable for a in args)
        if name == "coalesce":
            if any(not a.known for a in args):
                return ColType(None, all(a.nullable for a in args))
            tgt = ex.coalesce_common_type(
                list(e.args), [a.ctype for a in args])
            return ColType(tgt, all(a.nullable for a in args))
        if name == "like":
            return ColType(BOOL, args[0].nullable if args else True)
        if name in ("substr", "substring", "upper", "lower", "trim",
                    "concat"):
            return ColType(STRING, any_null)
        if name == "length":
            return ColType(INT32, args[0].nullable if args else True)
        if name == "abs":
            return args[0] if args else UNKNOWN
        if name == "round":
            if not args or not args[0].known:
                return UNKNOWN
            a = args[0]
            if a.kind == "decimal":
                nd = 0
                if len(e.args) > 1 and isinstance(e.args[1], ex.Literal):
                    nd = int(e.args[1].value)
                if nd >= a.ctype.scale:
                    return a
                return ColType(decimal(a.ctype.precision, nd), a.nullable)
            return ColType(FLOAT64, a.nullable)
        if name in ("floor", "ceil", "sqrt"):
            return ColType(FLOAT64, args[0].nullable if args else True)
        if name in ("year", "month", "day"):
            return ColType(INT32, args[0].nullable if args else True)
        if name == "nullif":
            a = args[0] if args else UNKNOWN
            return ColType(a.ctype, True)
        if name == "grouping":
            return ColType(INT32, False)
        return UNKNOWN


def infer_plan(plan: lp.Plan, tables: Dict[str, object], query: str = "",
               scale_factor: Optional[float] = None
               ) -> Tuple[Schema, List[Diagnostic]]:
    """Infer the output schema of ``plan`` and return typing diagnostics.

    ``tables`` maps table name -> :class:`ndstpu.schema.TableSchema`
    (e.g. ``schema.get_schemas()`` merged with
    ``schema.get_maintenance_schemas()``).
    """
    tc = TypeChecker(tables, query=query, scale_factor=scale_factor)
    out = tc.infer(plan)
    return out, tc.diags
