"""Static cost model: calibrated cardinality/byte estimation (NDS6xx).

Bottom-up row-count / byte / selectivity estimation over canonical
logical plans — the static half of the adaptive-execution story the
reference harness delegates wholesale to Spark AQE (ROADMAP item 4).
Everything runs over the ZERO-ROW schema catalog: SF-scaled base
cardinalities come from the dsdgen table of contents
(:data:`~ndstpu.analysis.spines.SF1_ROWS`), filter selectivities from
per-predicate-class heuristics, join fan-out from key-domain NDV
(surrogate-key columns resolve to their referenced dimension's row
count), and aggregate/distinct group counts from per-column NDV
heuristics.  Estimates are *calibrated* when a run ledger with
observed output cardinalities is available (:class:`Calibration`):
the per-query observed/estimated ratio recenters the estimate and the
cross-query ratio dispersion replaces the model's coarse confidence
band.

Diagnostic family (registered in analysis/diagnostics.py, swept by
scripts/cost_lint.py into COST_LINT.json / COST_LINT.md):

======= ==============================================================
NDS601  broadcast build side over the replication byte budget
        (memplan's device budget x :data:`BROADCAST_FRACTION`) — the
        cost model demotes it to the shuffle (all_to_all) path
NDS602  spill-risk working set: predicted per-device bytes
        (memplan's COMPUTE_MULT model + resident broadcast builds)
        exceed the device budget, so the plan must stream out-of-core
NDS603  exchange-heavy plan: predicted collective (all_to_all) bytes
        across shuffle-placed joins exceed the heavy-traffic threshold
NDS604  misestimate: static estimate vs ledger-observed output
        cardinality beyond :data:`MISESTIMATE_RATIO` (only emitted
        when calibration data is supplied — scripts/cost_lint.py
        --calibrate)
======= ==============================================================

The same :func:`choose_strategy` is consumed by BOTH the static
analyzer (lowering.py's upgraded NDS305 placement prediction) and the
runtime executor (parallel/dplan.py's :class:`CostAdvisor`), the
repo's usual single-source-of-truth idiom, so what the analyzer
predicts and what the runtime picks cannot drift.  ``NDSTPU_COST=0``
disables the runtime consumers (fixed structural rules, the
pre-cost-model behavior); the static lint is always available.

Import-hygienic like the rest of ``ndstpu.analysis``: numpy only, no
jax — :func:`cost_budget_bytes` reads env/defaults instead of probing
a device (mirror of spines.spine_budget_bytes).
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from ndstpu.engine import columnar, memplan, plan as lp
from ndstpu.engine import expr as ex
from ndstpu.analysis.diagnostics import Diagnostic
from ndstpu.analysis.spines import SF1_ROWS, _SCALED_TABLES
from ndstpu.analysis.typecheck import TypeChecker, _child_path

__all__ = [
    "BROADCAST_FRACTION", "Calibration", "CostAdvisor", "CostEstimate",
    "CostModel", "CostReport", "Decision", "JoinPlacement",
    "MISESTIMATE_RATIO", "audit_cost", "choose_strategy",
    "cost_budget_bytes", "default_advisor", "enabled",
    "misestimate_diags", "observed_rows_from_ledger",
]

# -- tuning constants --------------------------------------------------------

#: fraction of the device budget a replicated (broadcast) build side may
#: occupy — the rest is the spine's streaming working set
BROADCAST_FRACTION = 0.25

#: predicted collective traffic above this fraction of the device
#: budget marks a plan exchange-heavy (NDS603)
EXCHANGE_HEAVY_FRACTION = 0.5

#: observed/estimated cardinality ratio beyond which NDS604 fires
MISESTIMATE_RATIO = 4.0

#: selectivity heuristics per predicate class (Selinger-style defaults;
#: equality resolves through column NDV when the column is recognized)
SEL_EQ = 0.05
SEL_RANGE = 1.0 / 3.0
SEL_NEQ = 0.9
SEL_LIKE = 0.15
SEL_NULL = 0.02
SEL_IN_PARAM = 0.2
SEL_SUBQUERY = 0.5
SEL_DEFAULT = 0.25

#: floor so stacked predicates never estimate to zero rows
SEL_FLOOR = 1e-4

#: anti-join survivor floor (a filterless anti join rarely drops all)
ANTI_FLOOR = 0.05

#: confidence band doubles per heuristic step, capped at 2**6 = 64x
MAX_BAND_STEPS = 6

#: per-column NDV by name fragment (TPC-DS date/demographic attributes)
_NAME_NDV = {
    "year": 200, "qoy": 4, "moy": 12, "dom": 31, "dow": 7,
    "quarter": 4, "month": 12, "gender": 2, "marital": 5,
    "education": 7, "state": 50, "county": 200, "country": 200,
}

#: surrogate-key suffix -> referenced dimension (key domain = that
#: table's row count; suffixes checked longest-first so e.g.
#: ``cdemo_sk`` never falls through to a shorter match)
_SK_REF_TABLES = {
    "item_sk": "item", "date_sk": "date_dim", "time_sk": "time_dim",
    "store_sk": "store", "customer_sk": "customer",
    "cdemo_sk": "customer_demographics",
    "hdemo_sk": "household_demographics",
    "addr_sk": "customer_address", "promo_sk": "promotion",
    "warehouse_sk": "warehouse", "web_site_sk": "web_site",
    "web_page_sk": "web_page", "call_center_sk": "call_center",
    "ship_mode_sk": "ship_mode", "reason_sk": "reason",
    "catalog_page_sk": "catalog_page", "income_band_sk": "income_band",
    "band_sk": "income_band",
}
_SK_SUFFIXES = sorted(_SK_REF_TABLES, key=len, reverse=True)


def enabled() -> bool:
    """Runtime kill switch: ``NDSTPU_COST=0`` restores the fixed
    structural rules in dplan/memplan (bit-identical results — the
    cost model only picks among semantically equivalent strategies)."""
    return os.environ.get("NDSTPU_COST", "1") != "0"


def cost_budget_bytes() -> Tuple[int, str]:
    """Per-device byte budget for the static passes and where it came
    from: ``NDSTPU_COST_BUDGET_BYTES`` (tests / operator pin), then
    ``NDSTPU_HBM_BYTES`` x memplan.SAFETY, then the memplan default x
    SAFETY.  Never probes a device — the analyzer must run jax-free."""
    env = os.environ.get("NDSTPU_COST_BUDGET_BYTES")
    if env:
        return max(int(env), 1), "env"
    hbm = os.environ.get("NDSTPU_HBM_BYTES")
    if hbm:
        return max(int(int(hbm) * memplan.SAFETY), 1), "hbm"
    return int(memplan.DEFAULT_BUDGET_BYTES * memplan.SAFETY), "default"


# ---------------------------------------------------------------------------
# estimates
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class CostEstimate:
    """Estimated output cardinality with a multiplicative confidence
    band: the model believes the true row count lies in
    ``[rows * lo, rows * hi]``."""

    rows: float
    row_bytes: Optional[int] = None
    lo: float = 1.0
    hi: float = 1.0

    @property
    def bytes(self) -> Optional[int]:
        if self.row_bytes is None:
            return None
        return int(self.rows * self.row_bytes)


@dataclasses.dataclass(frozen=True)
class Calibration:
    """Per-query observed/estimated output-cardinality ratios from the
    run ledger, plus their cross-query geometric dispersion — the
    replacement confidence band for calibrated queries."""

    ratios: Dict[str, float]
    dispersion: float = 2.0

    @classmethod
    def from_pairs(cls, estimated: Dict[str, float],
                   observed: Dict[str, float]) -> "Calibration":
        ratios = {}
        for q, est in estimated.items():
            obs = observed.get(q)
            if obs is None or est is None:
                continue
            ratios[q] = float(obs) / max(float(est), 1.0)
        if ratios:
            logs = [math.log(max(r, 1e-9)) for r in ratios.values()]
            mu = sum(logs) / len(logs)
            var = sum((v - mu) ** 2 for v in logs) / len(logs)
            disp = max(math.exp(math.sqrt(var)), 1.25)
        else:
            disp = 2.0
        return cls(ratios=ratios, dispersion=disp)

    @classmethod
    def from_ledger(cls, path: str,
                    estimated: Dict[str, float]) -> "Calibration":
        return cls.from_pairs(estimated, observed_rows_from_ledger(path))


def observed_rows_from_ledger(path: str) -> Dict[str, float]:
    """query -> last observed output row count, from ledger entries
    whose ``extra.result_rows`` was recorded by the harness (power.py
    annotates every successful query's result cardinality)."""
    out: Dict[str, float] = {}
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    e = json.loads(line)
                except ValueError:
                    continue
                rows = (e.get("extra") or {}).get("result_rows")
                if rows is None or not e.get("query"):
                    continue
                out[e["query"]] = float(rows)
    except OSError:
        pass
    return out


def misestimate_diags(estimated: Dict[str, CostEstimate],
                      observed: Dict[str, float],
                      threshold: float = MISESTIMATE_RATIO
                      ) -> List[Diagnostic]:
    """NDS604 per query whose observed output cardinality falls outside
    ``threshold`` x the static estimate (in either direction)."""
    diags: List[Diagnostic] = []
    for q in sorted(estimated):
        obs = observed.get(q)
        if obs is None:
            continue
        est = max(estimated[q].rows, 1.0)
        ratio = max(float(obs), 1.0) / est
        if ratio > threshold or ratio < 1.0 / threshold:
            diags.append(Diagnostic(
                code="NDS604",
                message=f"static estimate {est:.0f} rows vs observed "
                        f"{obs:.0f} (ratio {ratio:.2f} beyond "
                        f"{threshold:g}x): recalibrate or revisit the "
                        "selectivity class",
                path="Plan", query=q))
    return diags


class CostModel:
    """Bottom-up per-node cardinality/byte estimator over one plan.

    ``row_counts`` overrides the SF-scaled dsdgen base cardinalities
    with actual per-table counts (the runtime agreement tests hand in
    the loaded warehouse's sizes so static and runtime decisions are
    comparable on tiny fixtures)."""

    def __init__(self, tables: Dict[str, object],
                 scale_factor: Optional[float] = None,
                 row_counts: Optional[Dict[str, int]] = None,
                 calibration: Optional[Calibration] = None,
                 query: str = ""):
        self.tables = tables
        self.sf = scale_factor
        self.row_counts = dict(row_counts or {})
        self.calibration = calibration
        self.query = query
        self.tc = TypeChecker(tables, query=query,
                              scale_factor=scale_factor)
        self._memo: Dict[int, CostEstimate] = {}

    # -- base cardinalities --------------------------------------------------

    def base_rows(self, table: str) -> Optional[float]:
        if table in self.row_counts:
            return float(self.row_counts[table])
        base = SF1_ROWS.get(table)
        if base is None:
            return None
        if self.sf and table in _SCALED_TABLES:
            base = base * self.sf
        return float(base)

    # -- NDV heuristics ------------------------------------------------------

    def column_ndv(self, name: str, owner_rows: float) -> float:
        """Distinct-value estimate for one column: surrogate keys span
        their referenced dimension, recognized date/demographic
        attributes use fixed domains, everything else falls back to
        the square-root heuristic."""
        low = name.lower()
        for suf in _SK_SUFFIXES:
            if low.endswith(suf):
                ref = self.base_rows(_SK_REF_TABLES[suf])
                if ref is not None:
                    return max(ref, 1.0)
                break
        for frag, ndv in _NAME_NDV.items():
            if frag in low:
                return float(min(ndv, max(owner_rows, 1.0)))
        return float(min(max(math.sqrt(max(owner_rows, 1.0)), 2.0),
                         max(owner_rows, 1.0)))

    def _owner_rows(self, name: str, scans: List[lp.Scan]) -> float:
        """Unfiltered row count of the base table owning ``name``."""
        for s in scans:
            ts = self.tables.get(s.table)
            if ts is not None and any(c.name == name
                                      for c in ts.columns):
                r = self.base_rows(s.table)
                if r is not None:
                    return r
        best = 0.0
        for s in scans:
            r = self.base_rows(s.table)
            if r:
                best = max(best, r)
        return best or 1000.0

    def _expr_ndv(self, e: ex.Expr, scans: List[lp.Scan]) -> float:
        cols = [nd.name for nd in e.walk() if isinstance(nd, ex.ColumnRef)]
        if not cols:
            return 2.0
        return max(self.column_ndv(c, self._owner_rows(c, scans))
                   for c in cols)

    # -- selectivity ---------------------------------------------------------

    def selectivity(self, e: ex.Expr, scans: List[lp.Scan]) -> float:
        """Fraction of rows a boolean predicate keeps, by predicate
        class; AND multiplies (independence), OR is inclusion-
        exclusion, NOT complements."""
        return float(min(max(self._sel(e, scans), SEL_FLOOR), 1.0))

    def _sel(self, e: ex.Expr, scans: List[lp.Scan]) -> float:
        if isinstance(e, ex.BinOp):
            op = e.op
            if op == "and":
                return self._sel(e.left, scans) * self._sel(e.right, scans)
            if op == "or":
                s1 = self._sel(e.left, scans)
                s2 = self._sel(e.right, scans)
                return s1 + s2 - s1 * s2
            if op == "=":
                for side in (e.left, e.right):
                    if isinstance(side, ex.ColumnRef):
                        ndv = self.column_ndv(
                            side.name, self._owner_rows(side.name, scans))
                        return 1.0 / max(ndv, 1.0 / SEL_EQ)
                return SEL_EQ
            if op == "<>":
                return SEL_NEQ
            if op in ("<", "<=", ">", ">="):
                return SEL_RANGE
            return 1.0
        if isinstance(e, ex.UnaryOp):
            if e.op == "not":
                return 1.0 - self._sel(e.operand, scans)
            if e.op == "isnull":
                return SEL_NULL
            if e.op == "isnotnull":
                return 1.0 - SEL_NULL
            return 1.0
        if isinstance(e, ex.InList):
            ndv = self._expr_ndv(e.operand, scans)
            s = min(len(e.values) / max(ndv, 1.0), 0.5)
            return (1.0 - s) if e.negated else s
        if isinstance(e, ex.InParam):
            return SEL_IN_PARAM
        if isinstance(e, ex.Func) and e.name == "like":
            return SEL_LIKE
        if isinstance(e, ex.SubqueryExpr):
            return SEL_SUBQUERY
        if isinstance(e, ex.Literal):
            if e.value is True:
                return 1.0
            if e.value is False:
                return 0.0
            return 1.0
        if isinstance(e, ex.Case):
            return SEL_DEFAULT
        return SEL_DEFAULT

    # -- per-node estimation -------------------------------------------------

    def estimate(self, node: lp.Plan) -> CostEstimate:
        """Estimated output of ``node``'s subtree (memoized by node
        identity — plans are DAG-free trees)."""
        got = self._memo.get(id(node))
        if got is None:
            got = self._estimate(node)
            self._memo[id(node)] = got
        return got

    def estimate_query(self, plan: lp.Plan) -> CostEstimate:
        """Root estimate with the confidence band attached: the band
        doubles per heuristic step (filter/join/aggregate/distinct),
        capped at 2**:data:`MAX_BAND_STEPS`; a calibrated query instead
        recenters on the ledger-observed ratio and carries the
        calibration set's dispersion as its band."""
        est = self.estimate(plan)
        steps = sum(
            1 for n in plan.walk()
            if isinstance(n, (lp.Filter, lp.Join, lp.Aggregate,
                              lp.Distinct))
            or (isinstance(n, lp.Scan) and n.predicate is not None))
        k = min(steps, MAX_BAND_STEPS)
        rows, lo, hi = est.rows, 2.0 ** -k, 2.0 ** k
        if self.calibration is not None:
            ratio = self.calibration.ratios.get(self.query)
            if ratio is not None:
                d = self.calibration.dispersion
                rows, lo, hi = rows * ratio, 1.0 / d, d
        return CostEstimate(rows=rows, row_bytes=est.row_bytes,
                            lo=lo, hi=hi)

    def _row_bytes(self, node: lp.Plan) -> Optional[int]:
        """Output row width through memplan's model (string columns
        count their int32 dict-code width, the device-resident form)."""
        try:
            schema = self.tc.infer(node)
        except Exception:  # noqa: BLE001 — width is advisory
            return None
        if not schema.known:
            return None
        sizes = []
        for _, ct in schema.cols:
            if ct.ctype is None:
                return None
            sizes.append(np.dtype(
                columnar.numpy_dtype(ct.ctype)).itemsize)
        return memplan.row_bytes(sizes)

    def _scans(self, node: lp.Plan) -> List[lp.Scan]:
        return [n for n in node.walk() if isinstance(n, lp.Scan)]

    def _estimate(self, node: lp.Plan) -> CostEstimate:
        rb = self._row_bytes(node)
        if isinstance(node, lp.Scan):
            rows = self.base_rows(node.table)
            rows = 1000.0 if rows is None else rows
            if node.predicate is not None:
                rows *= self.selectivity(node.predicate, [node])
            return CostEstimate(max(rows, 0.0), rb)
        if isinstance(node, lp.InlineTable):
            n = getattr(node.table, "num_rows", None)
            return CostEstimate(float(n if n is not None else 10), rb)
        if isinstance(node, lp.Filter):
            child = self.estimate(node.child)
            sel = self.selectivity(node.condition,
                                   self._scans(node.child))
            return CostEstimate(child.rows * sel, rb)
        if isinstance(node, lp.Join):
            return self._estimate_join(node, rb)
        if isinstance(node, lp.Aggregate):
            child = self.estimate(node.child)
            scans = self._scans(node.child)
            if not node.group_by:
                groups = 1.0
            else:
                groups = 1.0
                for _, e in node.group_by:
                    groups = min(groups * self._expr_ndv(e, scans),
                                 2.0 ** 62)
                groups = min(groups, max(child.rows, 1.0))
            if node.grouping_sets:
                groups = min(groups * len(node.grouping_sets),
                             max(child.rows, 1.0) *
                             len(node.grouping_sets))
            return CostEstimate(groups, rb)
        if isinstance(node, lp.Distinct):
            child = self.estimate(node.child)
            return CostEstimate(
                min(child.rows, max(child.rows * 0.1, 1.0)), rb)
        if isinstance(node, lp.Limit):
            child = self.estimate(node.child)
            n = node.n if node.n else 0
            return CostEstimate(min(child.rows, float(n))
                                if n else child.rows, rb)
        if isinstance(node, lp.SetOp):
            left = self.estimate(node.left)
            right = self.estimate(node.right)
            if node.kind == "union":
                rows = left.rows + right.rows
                if not node.all:
                    rows *= 0.9
            elif node.kind == "intersect":
                rows = min(left.rows, right.rows) * 0.5
            else:  # except
                rows = left.rows * 0.5
            return CostEstimate(rows, rb)
        if isinstance(node, lp.DeviceResult):
            return CostEstimate(1000.0, rb)
        kids = node.children()
        if kids:
            child = self.estimate(kids[0])
            return CostEstimate(child.rows, rb)
        return CostEstimate(1000.0, rb)

    def _estimate_join(self, node: lp.Join,
                       rb: Optional[int]) -> CostEstimate:
        left = self.estimate(node.left)
        right = self.estimate(node.right)
        l, r = max(left.rows, 0.0), max(right.rows, 0.0)
        if node.kind == "cross" or not node.keys:
            rows = l * r if node.kind in ("cross", "inner") else l
            return CostEstimate(rows, rb)
        lscans = self._scans(node.left)
        rscans = self._scans(node.right)
        ndv_l = ndv_r = 1.0
        for le, re_ in node.keys:
            ndv_l = min(ndv_l * self._expr_ndv(le, lscans), 2.0 ** 62)
            ndv_r = min(ndv_r * self._expr_ndv(re_, rscans), 2.0 ** 62)
        domain = max(ndv_l, ndv_r, 1.0)
        inner = l * r / domain
        coverage = min(r / domain, 1.0)    # P(probe key has a match)
        kind = node.kind
        if kind == "inner":
            rows = inner
        elif kind == "left":
            rows = max(inner, l)
        elif kind == "right":
            rows = max(inner, r)
        elif kind == "full":
            rows = max(inner, l + r)
        elif kind == "semi":
            rows = l * coverage
        elif kind in ("anti", "nullaware_anti"):
            rows = l * max(1.0 - coverage, ANTI_FLOOR)
        elif kind == "mark":
            rows = l
        else:
            rows = inner
        return CostEstimate(max(rows, 0.0), rb)


# ---------------------------------------------------------------------------
# strategy choice (shared: analysis NDS305 prediction + dplan runtime)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Decision:
    """One exchange-placement decision for a spine join."""

    strategy: str       # broadcast | shuffle | build-reduce
    structural: str     # what the fixed pre-cost rule would pick
    reason: str

    @property
    def overrode(self) -> bool:
        return self.strategy != self.structural


def choose_strategy(build_rows: float, build_bytes: Optional[float], *,
                    broadcast_limit_rows: int, budget_bytes: int,
                    reducible: bool = False) -> Decision:
    """Exchange placement for one spine join's build side.

    The structural (pre-cost) rule is rows-only: over the broadcast
    row limit -> shuffle, else broadcast.  The cost model adds the byte
    dimension: a build whose replicated footprint exceeds
    :data:`BROADCAST_FRACTION` of the device budget is demoted to the
    shuffle path even under the row limit (NDS601).  ``reducible``
    marks an existence-join build containing a sharded-size table —
    the distributed distinct-key reduction (dplan._reduce_build) wins
    outright.  Demote-only by design: the shuffle->broadcast promotion
    direction is never taken, so operator-forced shuffle coverage
    (tests pinning ``broadcast_limit_rows``) keeps its meaning."""
    structural = "shuffle" if build_rows > broadcast_limit_rows \
        else "broadcast"
    if reducible:
        return Decision("build-reduce", structural,
                        "existence build reduces to distinct key "
                        "tuples distributed")
    bcast_budget = int(budget_bytes * BROADCAST_FRACTION)
    if build_bytes is not None and build_bytes > bcast_budget:
        return Decision(
            "shuffle", structural,
            f"build ~{int(build_bytes)} B over the {bcast_budget} B "
            "replication budget")
    if structural == "shuffle":
        return Decision("shuffle", structural,
                        "build rows over the broadcast limit")
    return Decision("broadcast", structural,
                    "build under the broadcast row limit and "
                    "replication budget")


@dataclasses.dataclass
class CostAdvisor:
    """Runtime strategy chooser handed to dplan (see
    :func:`default_advisor`).  Decisions are recorded by the executor
    (``engine.cost.decisions`` / ``engine.cost.overrides`` counters,
    ``cost_decisions`` span attr -> ledger extra)."""

    broadcast_limit_rows: int
    budget_bytes: int
    calibration: Optional[Calibration] = None

    def decide_join(self, *, build_rows: int,
                    build_bytes: Optional[int], kind: str,
                    dup_max: int, order_safe: bool) -> Decision:
        d = choose_strategy(build_rows, build_bytes,
                            broadcast_limit_rows=self.broadcast_limit_rows,
                            budget_bytes=self.budget_bytes)
        if not d.overrode:
            return d
        if not order_safe:
            # a row-spine's output order depends on where rows live;
            # only aggregate spines may re-place safely
            return Decision(d.structural, d.structural,
                            "cost override suppressed: "
                            "row-order-sensitive spine")
        if d.strategy == "shuffle" and dup_max and kind == "inner":
            # the shuffle path cannot expand duplicate build key runs
            return Decision(d.structural, d.structural,
                            "cost override suppressed: expanding "
                            "inner join cannot shuffle")
        return d


def default_advisor(broadcast_limit_rows: int,
                    calibration: Optional[Calibration] = None
                    ) -> CostAdvisor:
    """Advisor over the *runtime* device budget (memplan probes the
    backend here — this is the jax-loaded side of the fence)."""
    budget, _src = memplan.device_budget_bytes()
    return CostAdvisor(
        broadcast_limit_rows=broadcast_limit_rows,
        budget_bytes=int(budget * memplan.SAFETY),
        calibration=calibration)


# ---------------------------------------------------------------------------
# static plan audit (NDS601/602/603)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class JoinPlacement:
    """Predicted exchange placement for one spine join."""

    path: str
    kind: str
    build_rows: float
    build_bytes: Optional[int]
    decision: Decision


@dataclasses.dataclass
class CostReport:
    """Static cost audit of one query part (scripts/cost_lint.py)."""

    query: str
    root: CostEstimate
    placements: List[JoinPlacement]
    working_set_bytes: Optional[int]
    exchange_bytes: int
    budget_bytes: int
    diagnostics: List[Diagnostic]

    def placement_counts(self) -> Dict[str, int]:
        out = {"broadcast": 0, "shuffle": 0, "build-reduce": 0}
        for p in self.placements:
            out[p.decision.strategy] += 1
        return out

    def as_dict(self) -> dict:
        return {
            "est_rows": round(self.root.rows, 1),
            "band": [round(self.root.lo, 4), round(self.root.hi, 4)],
            "row_bytes": self.root.row_bytes,
            "working_set_bytes": self.working_set_bytes,
            "exchange_bytes": self.exchange_bytes,
            "placements": [
                {"path": p.path, "kind": p.kind,
                 "build_rows": round(p.build_rows, 1),
                 "build_bytes": p.build_bytes,
                 "strategy": p.decision.strategy,
                 "structural": p.decision.structural,
                 "reason": p.decision.reason}
                for p in self.placements],
        }


def _walk_paths(node: lp.Plan,
                path: str = "") -> Iterator[Tuple[lp.Plan, str]]:
    path = path or type(node).__name__
    yield node, path
    for i, c in enumerate(node.children()):
        yield from _walk_paths(c, _child_path(path, c, i))


def audit_cost(plan: lp.Plan,
               tables: Optional[Dict[str, object]] = None,
               query: str = "",
               scale_factor: Optional[float] = None,
               budget_bytes: Optional[int] = None,
               n_dev: int = 1,
               broadcast_limit_rows: Optional[int] = None,
               shard_threshold_rows: int = 65536,
               row_counts: Optional[Dict[str, int]] = None,
               calibration: Optional[Calibration] = None) -> CostReport:
    """Static cost audit of one optimized plan: root estimate, per-join
    exchange placement (mirroring dplan._prepare's decision points via
    the shared :func:`choose_strategy`), predicted working set, and the
    NDS601/NDS602/NDS603 diagnostics."""
    from ndstpu.analysis import lowering as lowreg

    if tables is None:
        from ndstpu import analysis
        tables = analysis.schema_tables()
    if budget_bytes is None:
        budget_bytes, _src = cost_budget_bytes()
    if broadcast_limit_rows is None:
        broadcast_limit_rows = lowreg.SPMD_BROADCAST_LIMIT_ROWS
    model = CostModel(tables, scale_factor=scale_factor,
                      row_counts=row_counts, calibration=calibration,
                      query=query)
    root = model.estimate_query(plan)
    diags: List[Diagnostic] = []
    placements: List[JoinPlacement] = []

    # candidate sharded fact: largest base table over the shard
    # threshold (dplan tries largest-first; the first candidate is the
    # one the static placement prediction anchors on)
    target: Optional[lp.Scan] = None
    target_path = type(plan).__name__
    best = -1.0
    for node, npath in _walk_paths(plan):
        if isinstance(node, lp.Scan):
            rows = model.base_rows(node.table) or 0.0
            if rows >= shard_threshold_rows and rows > best:
                best, target, target_path = rows, node, npath
    working_set: Optional[int] = None
    exchange = 0
    if target is not None:
        bcast_budget = int(budget_bytes * BROADCAST_FRACTION)
        bcast_bytes = 0
        fact_est = model.estimate(target)
        for node, npath in _walk_paths(plan):
            if not isinstance(node, lp.Join):
                continue
            in_l = any(n is target for n in node.left.walk())
            in_r = any(n is target for n in node.right.walk())
            if in_l == in_r:       # neither side, or a self-join artifact
                continue
            if node.kind not in lowreg.SPMD_SPINE_JOIN_KINDS \
                    or not node.keys:
                continue
            if in_r and node.kind != "inner":
                if node.kind in lowreg.SPMD_REDUCIBLE_BUILD_JOIN_KINDS \
                        and not (node.kind == "nullaware_anti"
                                 and node.extra is not None):
                    # probe-anchored elsewhere, this build reduces to
                    # its distinct key tuples (NDS308 / _reduce_build)
                    best_build = model.estimate(node.right)
                    placements.append(JoinPlacement(
                        path=npath, kind=node.kind,
                        build_rows=best_build.rows,
                        build_bytes=best_build.bytes,
                        decision=choose_strategy(
                            best_build.rows, best_build.bytes,
                            broadcast_limit_rows=broadcast_limit_rows,
                            budget_bytes=budget_bytes,
                            reducible=True)))
                continue           # non-reducible: single-chip fallback
            build = node.left if in_r else node.right
            est = model.estimate(build)
            reducible = (
                node.kind in lowreg.SPMD_REDUCIBLE_BUILD_JOIN_KINDS
                and not (node.kind == "nullaware_anti"
                         and node.extra is not None)
                and any(isinstance(n, lp.Scan)
                        and (model.base_rows(n.table) or 0.0)
                        >= shard_threshold_rows
                        for n in build.walk()))
            d = choose_strategy(est.rows, est.bytes,
                                broadcast_limit_rows=broadcast_limit_rows,
                                budget_bytes=budget_bytes,
                                reducible=reducible)
            placements.append(JoinPlacement(
                path=npath, kind=node.kind, build_rows=est.rows,
                build_bytes=est.bytes, decision=d))
            if d.structural == "broadcast" and est.bytes is not None \
                    and est.bytes > bcast_budget:
                diags.append(Diagnostic(
                    code="NDS601",
                    message=f"broadcast build ~{est.bytes} B over the "
                            f"{bcast_budget} B replication budget "
                            f"({budget_bytes} B device budget x "
                            f"{BROADCAST_FRACTION:g}): cost model "
                            "places it on the shuffle path",
                    path=npath, query=query))
            if d.strategy == "broadcast" and est.bytes is not None:
                bcast_bytes += est.bytes
            if d.strategy == "shuffle":
                exchange += int(est.bytes or 0) + int(fact_est.bytes or 0)
        if fact_est.row_bytes is not None:
            shard_rows = math.ceil(max(fact_est.rows, 1.0)
                                   / max(n_dev, 1))
            working_set = int(shard_rows * fact_est.row_bytes
                              * memplan.COMPUTE_MULT) + bcast_bytes
            if working_set > budget_bytes:
                diags.append(Diagnostic(
                    code="NDS602",
                    message=f"predicted per-device working set "
                            f"~{working_set} B (COMPUTE_MULT="
                            f"{memplan.COMPUTE_MULT} model + "
                            f"{bcast_bytes} B resident broadcast "
                            f"builds over {n_dev} device(s)) exceeds "
                            f"the {budget_bytes} B budget: the fact "
                            "must stream out-of-core",
                    path=target_path, query=query))
        heavy = int(budget_bytes * EXCHANGE_HEAVY_FRACTION)
        if exchange > heavy:
            diags.append(Diagnostic(
                code="NDS603",
                message=f"predicted collective (all_to_all) traffic "
                        f"~{exchange} B across shuffle-placed joins "
                        f"exceeds {heavy} B "
                        f"({EXCHANGE_HEAVY_FRACTION:g} x budget)",
                path=target_path, query=query))
    return CostReport(query=query, root=root, placements=placements,
                      working_set_bytes=working_set,
                      exchange_bytes=exchange,
                      budget_bytes=budget_bytes, diagnostics=diags)
