"""Deterministic, seed-driven fault injection at named sites.

The engine/harness layers carry ``faults.check(site)`` probes at the
places real production failures happen:

=====================  ====================================================
site                   probe location
=====================  ====================================================
``plan``               Session planning (parse/plan/optimize path)
``compile``            whole-query discovery/compile (jaxexec)
``execute``            statement execution (all backends)
``io.write``           artifact/table writes (atomic helper, transcode)
``io.read``            streaming scan chunk reads (loader ChunkSource)
``io.prefetch``        H2D staging ring background stage (jaxexec)
``exchange.collective``SPMD shuffle/broadcast/psum trace sites
``stream.worker``      in-process throughput stream worker entry
``phase.subprocess``   bench driver phase subprocess launch
``ingest.commit``      lake CAS commit publish (io/acid, io/deltalog)
``ingest.apply``       micro-batch ingest apply (harness/ingest)
``serve.accept``       query-server connection accept loop (serve/server)
``serve.dispatch``     query-server request dispatch, pre-retry — faults
                       here are client-visible and exercise client retry
``serve.replica.crash``whole-replica process death mid-dispatch
                       (os._exit) — the fleet supervisor restarts it,
                       clients fail over to a sibling (serve/fleet)
``fleet.probe``        fleet supervisor health probe — exercises the
                       consecutive-failure threshold before a restart
=====================  ====================================================

A spec is a comma-separated rule list::

    NDSTPU_FAULTS="execute:transient:0.2:seed7,io.write:permanent:0.05"

Each rule is ``site:kind:prob[:seedN][:key=value...]`` where kind is
``transient`` | ``permanent`` | ``hang``.  Optional extras: ``times=N``
(stop firing after N injections at this site) and ``hang=S`` (seconds a
``hang`` fault sleeps; default 3600 — long enough for any watchdog).

Determinism: the fire/no-fire decision for the *n*-th probe hit at a
site is a pure function of ``(seed, site, n)`` — independent of wall
clock, PID, and thread interleaving of *other* sites — so a chaos run
with the same seed and the same per-site call sequence injects the
same faults.  Every injection ticks ``faults.injected.<site>.<kind>``
(+ ``faults.injected.total``) and prints one greppable ``[faults]``
line.
"""

from __future__ import annotations

import os
import random
import threading
import time
from typing import Dict, List, Optional

from ndstpu import obs

SITES = ("plan", "compile", "execute", "io.write", "io.read",
         "io.prefetch", "exchange.collective", "stream.worker",
         "phase.subprocess", "ingest.commit", "ingest.apply",
         "serve.accept", "serve.dispatch", "serve.replica.crash",
         "fleet.probe")

KINDS = ("transient", "permanent", "hang")

ENV_VAR = "NDSTPU_FAULTS"

DEFAULT_HANG_S = 3600.0


class FaultSpecError(ValueError):
    """A malformed NDSTPU_FAULTS spec / YAML faults block."""


class InjectedFault(RuntimeError):
    """Base class for synthetic faults (site + kind carried along)."""

    def __init__(self, message: str, site: str, kind: str):
        super().__init__(message)
        self.site = site
        self.kind = kind


class InjectedTransient(InjectedFault):
    """Synthetic transient fault — the taxonomy retries these."""

    def __init__(self, message: str, site: str):
        super().__init__(message, site, "transient")


class InjectedPermanent(InjectedFault):
    """Synthetic permanent fault — never retried, always classified."""

    def __init__(self, message: str, site: str):
        super().__init__(message, site, "permanent")


class FaultRule:
    """One parsed rule: fire ``kind`` at ``site`` with ``prob``."""

    def __init__(self, site: str, kind: str, prob: float,
                 seed: str = "0", times: Optional[int] = None,
                 hang_s: float = DEFAULT_HANG_S):
        if site not in SITES:
            raise FaultSpecError(
                f"unknown fault site {site!r} (sites: {', '.join(SITES)})")
        if kind not in KINDS:
            raise FaultSpecError(
                f"unknown fault kind {kind!r} (kinds: {', '.join(KINDS)})")
        if not (0.0 <= prob <= 1.0):
            raise FaultSpecError(f"fault prob must be in [0,1]: {prob}")
        self.site = site
        self.kind = kind
        self.prob = prob
        self.seed = str(seed)
        self.times = times
        self.hang_s = hang_s
        self.fired = 0

    def should_fire(self, call_index: int) -> bool:
        """Pure function of (seed, site, call_index): python's Mersenne
        seeding from a string is stable across runs and platforms."""
        if self.times is not None and self.fired >= self.times:
            return False
        if self.prob <= 0.0:
            return False
        if self.prob >= 1.0:
            return True
        r = random.Random(f"{self.seed}|{self.site}|{call_index}")
        return r.random() < self.prob

    def describe(self) -> str:
        d = f"{self.site}:{self.kind}:{self.prob:g}:seed{self.seed}"
        if self.times is not None:
            d += f":times={self.times}"
        if self.kind == "hang" and self.hang_s != DEFAULT_HANG_S:
            d += f":hang={self.hang_s:g}"
        return d


def _parse_rule(text: str) -> FaultRule:
    parts = [p.strip() for p in text.strip().split(":") if p.strip()]
    if len(parts) < 3:
        raise FaultSpecError(
            f"fault rule needs site:kind:prob (got {text!r})")
    site, kind = parts[0], parts[1]
    try:
        prob = float(parts[2])
    except ValueError:
        raise FaultSpecError(f"bad fault prob in {text!r}: {parts[2]!r}")
    seed = "0"
    times: Optional[int] = None
    hang_s = DEFAULT_HANG_S
    for extra in parts[3:]:
        if extra.startswith("seed"):
            seed = extra[len("seed"):] or "0"
        elif extra.startswith("times="):
            times = int(extra[len("times="):])
        elif extra.startswith("hang="):
            hang_s = float(extra[len("hang="):])
        else:
            raise FaultSpecError(
                f"unknown fault rule extra {extra!r} in {text!r} "
                f"(know: seedN, times=N, hang=S)")
    return FaultRule(site, kind, prob, seed=seed, times=times,
                     hang_s=hang_s)


def parse_spec(spec) -> List[FaultRule]:
    """Parse the env-string grammar or a YAML ``faults:`` block.

    Accepted shapes::

        "execute:transient:0.2:seed7,plan:permanent:0.1"      # env string
        [{"site": "execute", "kind": "transient", "prob": 0.2,
          "seed": 7, "times": 3, "hang_s": 2.0}, ...]          # YAML list
    """
    if spec is None:
        return []
    if isinstance(spec, str):
        return [_parse_rule(r) for r in spec.split(",") if r.strip()]
    if isinstance(spec, dict):  # single-rule mapping
        spec = [spec]
    rules = []
    for item in spec:
        if isinstance(item, str):
            rules.append(_parse_rule(item))
            continue
        if not isinstance(item, dict) or "site" not in item:
            raise FaultSpecError(f"bad fault rule entry: {item!r}")
        rules.append(FaultRule(
            item["site"], item.get("kind", "transient"),
            float(item.get("prob", 1.0)),
            seed=str(item.get("seed", "0")),
            times=item.get("times"),
            hang_s=float(item.get("hang_s", DEFAULT_HANG_S))))
    return rules


class Injector:
    """Holds the active rules + per-site deterministic call counters."""

    def __init__(self, rules: List[FaultRule],
                 sleep=time.sleep, out=print):
        self._lock = threading.Lock()
        self.rules = list(rules)
        self.calls: Dict[str, int] = {}
        self.injected: Dict[str, int] = {}
        self._by_site: Dict[str, List[FaultRule]] = {}
        for r in self.rules:
            self._by_site.setdefault(r.site, []).append(r)
        self._sleep = sleep
        self._out = out

    def check(self, site: str, key: Optional[str] = None) -> None:
        rules = self._by_site.get(site)
        if not rules:
            return
        with self._lock:
            n = self.calls.get(site, 0)
            self.calls[site] = n + 1
            fire = None
            for r in rules:
                if r.should_fire(n):
                    fire = r
                    r.fired += 1
                    self.injected[site] = self.injected.get(site, 0) + 1
                    break
        if fire is None:
            return
        what = f"{fire.kind} fault at {site}" + \
            (f" ({key})" if key else "") + f" [call {n}, {fire.describe()}]"
        obs.inc(f"faults.injected.{site}.{fire.kind}")
        obs.inc("faults.injected.total")
        self._out(f"[faults] injected {what}")
        if fire.kind == "hang":
            # simulated wedge: the probe just stops returning — real
            # protection (watchdogs, abandonment) must kick in
            self._sleep(fire.hang_s)
            return
        if fire.kind == "transient":
            raise InjectedTransient(f"injected {what}", site)
        raise InjectedPermanent(f"injected {what}", site)


# -- module-level active injector (zero-cost no-op when unset) ---------

_ACTIVE: Optional[Injector] = None


def active() -> Optional[Injector]:
    return _ACTIVE


def install(spec) -> Optional[Injector]:
    """Install an injector from a spec (string / YAML block / rule
    list); ``None`` or an empty spec uninstalls.  Returns the active
    injector (or None)."""
    global _ACTIVE
    rules = spec if isinstance(spec, list) and spec and \
        isinstance(spec[0], FaultRule) else parse_spec(spec)
    _ACTIVE = Injector(rules) if rules else None
    return _ACTIVE


def uninstall() -> None:
    global _ACTIVE
    _ACTIVE = None


def install_from_env() -> Optional[Injector]:
    return install(os.environ.get(ENV_VAR) or None)


def check(site: str, key: Optional[str] = None) -> None:
    """The probe: no-op unless a spec is installed."""
    if _ACTIVE is None:
        return
    _ACTIVE.check(site, key=key)


# subprocesses inherit NDSTPU_FAULTS; configure on first import so every
# probe in every process of a chaos run sees the same spec
install_from_env()
