"""Bounded deterministic retry + per-key quarantine (poison handling).

``run_with_retry`` wraps one operation (a query execution, a stream
worker): transient failures (ndstpu/faults/taxonomy.py) are retried up
to ``RetryPolicy.max_attempts`` with deterministic exponential backoff
(no jitter — chaos runs must be reproducible); permanent failures raise
immediately.  Counters: ``harness.retry.attempts`` (every extra
attempt), ``harness.retry.recovered`` (succeeded after retrying),
``harness.retry.exhausted`` (transient budget spent),
``harness.taxonomy.transient`` / ``harness.taxonomy.permanent`` (final
failures by class).

``Quarantine`` is the poison list: a key (query name) that keeps
failing — across retries, streams, and resumed runs of one harness
process — is quarantined after ``max_failures`` distinct final
failures.  The harness skips quarantined keys with an explicit
per-query ``partial_reason`` (they never silently vanish) and, per the
PR-4 invariant, a quarantined/failed key never publishes to shared
compile/plan caches.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from ndstpu import obs
from ndstpu.faults import taxonomy

DEFAULT_MAX_ATTEMPTS = 2
DEFAULT_BASE_BACKOFF_S = 0.05
DEFAULT_MAX_BACKOFF_S = 2.0
DEFAULT_QUARANTINE_FAILURES = 2

RETRY_ENV = "NDSTPU_RETRY_MAX"


class RetryPolicy:
    """Attempt budget + deterministic exponential backoff."""

    def __init__(self, max_attempts: int = DEFAULT_MAX_ATTEMPTS,
                 base_backoff_s: float = DEFAULT_BASE_BACKOFF_S,
                 max_backoff_s: float = DEFAULT_MAX_BACKOFF_S):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        self.max_attempts = max_attempts
        self.base_backoff_s = base_backoff_s
        self.max_backoff_s = max_backoff_s

    def backoff_s(self, attempt: int) -> float:
        """Backoff before retry number ``attempt`` (1-based: the wait
        after the first failure is ``backoff_s(1) = base``).  Pure
        doubling capped at ``max_backoff_s`` — no jitter, so two chaos
        runs with the same fault sequence take the same waits."""
        return min(self.base_backoff_s * (2 ** (attempt - 1)),
                   self.max_backoff_s)

    @classmethod
    def from_env(cls, env: Optional[dict] = None) -> "RetryPolicy":
        import os
        env = env if env is not None else os.environ
        try:
            n = int(env.get(RETRY_ENV, DEFAULT_MAX_ATTEMPTS))
        except ValueError:
            n = DEFAULT_MAX_ATTEMPTS
        return cls(max_attempts=max(n, 1))


class Quarantine:
    """Thread-safe per-key poison list shared across stream workers."""

    def __init__(self, max_failures: int = DEFAULT_QUARANTINE_FAILURES):
        self.max_failures = max_failures
        self._lock = threading.Lock()
        self._failures: Dict[str, List[str]] = {}

    def note_failure(self, key: str, klass: str) -> bool:
        """Record one *final* failure (post-retry) for ``key``; returns
        True when this failure tips the key into quarantine."""
        with self._lock:
            fails = self._failures.setdefault(key, [])
            fails.append(klass)
            if len(fails) == self.max_failures:
                obs.inc("harness.quarantine.queries")
                return True
            return False

    def is_quarantined(self, key: str) -> bool:
        with self._lock:
            return len(self._failures.get(key, ())) >= self.max_failures

    def failures(self, key: str) -> List[str]:
        with self._lock:
            return list(self._failures.get(key, ()))

    def reason(self, key: str) -> str:
        fails = self.failures(key)
        return (f"quarantined: {len(fails)} prior failure(s) "
                f"[{', '.join(fails)}] on this query key "
                f"(poison; max_failures={self.max_failures})")

    def snapshot(self) -> Dict[str, List[str]]:
        with self._lock:
            return {k: list(v) for k, v in self._failures.items()
                    if len(v) >= self.max_failures}


def run_with_retry(fn: Callable[[], object], key: str,
                   policy: Optional[RetryPolicy] = None,
                   quarantine: Optional[Quarantine] = None,
                   sleep: Callable[[float], None] = time.sleep,
                   out: Callable[[str], None] = print
                   ) -> Tuple[object, int]:
    """Run ``fn`` with the retry/quarantine contract.

    Returns ``(result, attempts)``.  On final failure the original
    exception is re-raised with two attributes attached for the report
    layer: ``taxonomy`` ("transient"|"permanent") and ``attempts``.
    """
    policy = policy or RetryPolicy()
    attempt = 0
    while True:
        attempt += 1
        try:
            result = fn()
        except BaseException as e:  # noqa: BLE001 — classified below
            klass = taxonomy.classify(e)
            if klass == taxonomy.TRANSIENT and \
                    attempt < policy.max_attempts:
                wait = policy.backoff_s(attempt)
                obs.inc("harness.retry.attempts")
                out(f"[retry] {key}: transient failure "
                    f"({type(e).__name__}: {e}) — attempt "
                    f"{attempt}/{policy.max_attempts}, retrying in "
                    f"{wait:g}s")
                sleep(wait)
                continue
            if klass == taxonomy.TRANSIENT:
                obs.inc("harness.retry.exhausted")
            obs.inc(f"harness.taxonomy.{klass}")
            # tag the enclosing query span so the sidecar/ledger/
            # sentinel can split `failed` into failed-<taxonomy>
            obs.annotate(error_taxonomy=klass, error_attempts=attempt)
            if quarantine is not None:
                quarantine.note_failure(key, klass)
            try:
                e.taxonomy = klass
                e.attempts = attempt
            except Exception:  # immutable exception type (rare)
                pass
            raise
        if attempt > 1:
            obs.inc("harness.retry.recovered")
            # surfaces in the ledger entry's extra: a recovered query's
            # timing includes the failed attempts' wall time
            obs.annotate(retry_attempts=attempt)
            out(f"[retry] {key}: recovered on attempt {attempt}")
        return result, attempt
