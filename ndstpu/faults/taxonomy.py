"""Failure taxonomy: every exception is ``transient`` or ``permanent``.

The split drives retry policy (ndstpu/faults/retry.py) and the sentinel
verdicts (``failed-transient`` / ``failed-permanent``):

* **transient** — the operation might succeed on retry: RPC/connection
  faults, deadlines/timeouts (including the power watchdog's
  abandonment ``TimeoutError``), device preemption, and injected
  transient faults.
* **permanent** — retrying cannot help: planner rejections
  (``PlanError``), engine capability gaps (``Unsupported`` /
  ``DistUnsupported``), typecheck/contract violations (``TypeError``,
  ``ValueError``, ...), and injected permanent faults.

Classification is by exception-class *name* along the MRO plus message
keywords — never by importing engine modules — so the taxonomy is
usable from lint/CI contexts that must not pull jax.  Unknown
exceptions default to **permanent**: silently retrying a logic bug
hides it, while a misclassified transient merely fails one run.
"""

from __future__ import annotations

from typing import Tuple

TRANSIENT = "transient"
PERMANENT = "permanent"

# exception class names (matched along the MRO) that are retry-worthy
TRANSIENT_TYPE_NAMES = frozenset({
    "InjectedTransient",
    # losing a lake commit race (io/commit.py): reload + rebase + retry
    "CommitConflict",
    "TimeoutError",
    "ConnectionError",
    "ConnectionResetError",
    "ConnectionAbortedError",
    # refused MUST stay transient: during a fleet replica restart a
    # connect races the new incarnation's bind, and a client that
    # treats refusal as permanent abandons a server that is seconds
    # from ready (serve/client.py failover; tests/test_serve.py)
    "ConnectionRefusedError",
    "BrokenPipeError",
    "InterruptedError",
    # socket.timeout: an alias of TimeoutError since 3.10, but the
    # class *name* along the MRO is "timeout" on older pickles/paths —
    # a dropped serve connection must never classify permanent
    "timeout",
})

# class names that are definitely not retry-worthy, checked FIRST so a
# permanent subclass of a broad builtin never sneaks into retries
PERMANENT_TYPE_NAMES = frozenset({
    "InjectedPermanent",
    "PlanError",
    "Unsupported",
    "DistUnsupported",
    "NotImplementedError",
    "SyntaxError",
    "TypeError",
    "ValueError",
    "KeyError",
    "AttributeError",
    "AssertionError",
})

# message substrings that mark an otherwise-unknown runtime error
# (e.g. jax.errors.JaxRuntimeError wrapping an RPC failure) transient
TRANSIENT_MESSAGE_KEYWORDS = (
    "deadline exceeded",
    "timed out",
    "timeout",
    "rpc",
    "unavailable",
    "connection reset",
    "connection closed",
    "connection refused",
    "connection aborted",
    "broken pipe",
    "socket closed",
    "preempt",
    "temporarily",
    "try again",
)


def _mro_names(exc_type: type) -> Tuple[str, ...]:
    return tuple(c.__name__ for c in getattr(exc_type, "__mro__",
                                             (exc_type,)))


def classify_name(type_name: str, message: str = "") -> str:
    """Classify from a class name (+ optional message) alone — the
    sentinel path, which only has the span's recorded ``error`` attr."""
    if type_name in PERMANENT_TYPE_NAMES:
        return PERMANENT
    if type_name in TRANSIENT_TYPE_NAMES:
        return TRANSIENT
    low = (message or "").lower()
    if any(k in low for k in TRANSIENT_MESSAGE_KEYWORDS):
        return TRANSIENT
    return PERMANENT


def classify(exc: BaseException) -> str:
    """Classify a live exception: explicit taxonomy attribute first
    (injected faults carry ``.kind``), then MRO names, then message."""
    kind = getattr(exc, "kind", None)
    if kind in (TRANSIENT, PERMANENT):
        return kind
    names = _mro_names(type(exc))
    for n in names:
        if n in PERMANENT_TYPE_NAMES:
            return PERMANENT
    for n in names:
        if n in TRANSIENT_TYPE_NAMES:
            return TRANSIENT
    return classify_name(names[0] if names else "", str(exc))
