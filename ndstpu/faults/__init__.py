"""Deterministic fault injection + failure taxonomy + retry/quarantine.

Three pillars (docs/ROBUSTNESS.md):

* :mod:`ndstpu.faults.injector` — named fault *sites* instrumented
  through the engine, io, and harness layers.  A seed-driven spec
  (``NDSTPU_FAULTS=execute:transient:0.2:seed7`` or a YAML block)
  raises synthetic transient/permanent/hang faults at those sites.
  Same seed => same fault sequence, so chaos runs are reproducible.
* :mod:`ndstpu.faults.taxonomy` — classify any exception as
  ``transient`` (retry-worthy: RPC/timeout/injected-transient) or
  ``permanent`` (plan/typecheck/unsupported — retrying cannot help).
* :mod:`ndstpu.faults.retry` — bounded deterministic backoff around a
  query runner, plus per-query-key quarantine (poison handling):
  a key that keeps failing is skipped with an explicit
  ``partial_reason`` and never publishes to shared caches.

The probe API is zero-cost when no spec is installed::

    from ndstpu import faults
    faults.check("execute", key=query_name)   # no-op unless configured
"""

from __future__ import annotations

from ndstpu.faults.injector import (  # noqa: F401
    SITES,
    FaultSpecError,
    InjectedFault,
    InjectedPermanent,
    InjectedTransient,
    Injector,
    active,
    check,
    install,
    install_from_env,
    parse_spec,
    uninstall,
)
from ndstpu.faults.retry import (  # noqa: F401
    Quarantine,
    RetryPolicy,
    run_with_retry,
)
from ndstpu.faults.taxonomy import classify, classify_name  # noqa: F401

__all__ = [
    "SITES", "FaultSpecError", "InjectedFault", "InjectedTransient",
    "InjectedPermanent", "Injector", "active", "check", "install",
    "install_from_env", "uninstall", "parse_spec",
    "classify", "classify_name",
    "RetryPolicy", "Quarantine", "run_with_retry",
]
