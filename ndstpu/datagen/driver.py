"""Data-generation driver CLI.

Mirrors the reference driver's interface and behaviors
(/root/reference/nds/nds_gen_data.py): local multiprocess fan-out of the
native generator, per-table subdirectory layout, incremental `--range`
generation merged from a temporary directory, `--update` refresh sets with
separate placement of the delete-date tables, and an overwrite guard.

The reference's `hdfs` mode (Hadoop MapReduce fan-out, GenTable.java) maps
here to two modes:

* `dist` — this host's slice of a multi-host run (use `--range`); chunk
  content is position-deterministic, so any assignment of children to
  hosts is valid.
* `pod` — the coordinator: `--hosts h1,h2,...` splits the child chunks
  into contiguous per-host slices (the NLineInputFormat analog,
  GenTable.java:188-209) and launches one `dist` driver per host via a
  launcher template (default `ssh`), all writing to a SHARED data_dir
  (the HDFS-target analog).  Merged output is byte-identical to a local
  run with the same scale/parallel/seed.
"""

from __future__ import annotations

import argparse
import os
import shlex
import shutil
import subprocess
import sys

from ndstpu import schema
from ndstpu.check import (
    check_build,
    get_abs_path,
    get_dir_size,
    parallel_value_type,
    valid_range,
)

SOURCE_TABLE_NAMES = schema.SOURCE_TABLE_NAMES
MAINTENANCE_TABLE_NAMES = schema.MAINTENANCE_TABLE_NAMES


def _fanout(args, range_start: int, range_end: int, data_dir: str,
            tool: str) -> None:
    """Run one `ndsgen` process per child chunk, concurrently."""
    procs = []
    for child in range(range_start, range_end + 1):
        cmd = [
            str(tool),
            "-scale", str(args.scale),
            "-dir", data_dir,
            "-parallel", str(args.parallel),
            "-child", str(child),
        ]
        if args.update:
            cmd += ["-update", str(args.update)]
        if args.seed is not None:
            cmd += ["-seed", str(args.seed)]
        procs.append(subprocess.Popen(cmd))
    for p in procs:
        p.wait()
        if p.returncode != 0:
            raise RuntimeError(f"ndsgen failed with return code {p.returncode}")


def _move_into_table_dirs(data_dir: str, range_start: int, range_end: int,
                          parallel: int, update: int | None) -> None:
    """Move `{table}_{child}_{parallel}.dat` chunks into per-table folders
    (reference: nds_gen_data.py:229-242)."""
    tables = MAINTENANCE_TABLE_NAMES if update else SOURCE_TABLE_NAMES
    for table in tables:
        tdir = os.path.join(data_dir, table)
        os.makedirs(tdir, exist_ok=True)
        for child in range(range_start, range_end + 1):
            src = os.path.join(data_dir, f"{table}_{child}_{parallel}.dat")
            if os.path.exists(src):
                # full destination path so a re-run with --overwrite_output
                # replaces existing chunks (os.rename semantics)
                shutil.move(src, os.path.join(tdir, os.path.basename(src)))


def _merge_temp_tables(temp_dir: str, parent_dir: str,
                       update: int | None) -> None:
    """Move a --range run's per-table content up into the parent data dir
    (reference: nds_gen_data.py:91-117)."""
    tables = MAINTENANCE_TABLE_NAMES if update else SOURCE_TABLE_NAMES
    for table in tables:
        src_dir = os.path.join(temp_dir, table)
        if not os.path.isdir(src_dir):
            continue
        dst_dir = os.path.join(parent_dir, table)
        os.makedirs(dst_dir, exist_ok=True)
        for f in os.listdir(src_dir):
            shutil.move(os.path.join(src_dir, f), os.path.join(dst_dir, f))
    shutil.rmtree(temp_dir, ignore_errors=True)


def _host_slices(parallel: int, hosts: list) -> list:
    """Contiguous child-chunk slice per host (NLineInputFormat analog:
    GenTable.java genInput writes one dsdgen command line per mapper)."""
    n = len(hosts)
    per = -(-parallel // n)
    out = []
    for i, host in enumerate(hosts):
        lo = i * per + 1
        hi = min((i + 1) * per, parallel)
        if lo <= hi:
            out.append((host, lo, hi))
    return out


def generate_pod(args) -> None:
    """Coordinator for multi-host generation over a shared filesystem:
    one `dist --range` driver per host, launched through the
    `--launcher` template (`{host}` substituted; the per-host slice
    command is appended as a single shell-quoted argument)."""
    hosts = [h for h in (args.hosts or "").split(",") if h]
    if not hosts:
        raise RuntimeError("pod mode requires --hosts h1,h2,...")
    data_dir = _prepare_data_dir(args.data_dir, args.overwrite_output)
    repo = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    procs = []
    for host, lo, hi in _host_slices(int(args.parallel), hosts):
        remote = [args.remote_python, "-m", "ndstpu.datagen.driver",
                  "dist", str(args.scale), str(args.parallel), data_dir,
                  "--range", f"{lo},{hi}"]
        if args.update:
            remote += ["--update", str(args.update)]
        if args.seed is not None:
            remote += ["--seed", str(args.seed)]
        cmd = shlex.split(args.launcher.format(host=host)) + [
            "cd " + shlex.quote(repo) + " && PYTHONPATH=" +
            shlex.quote(repo) + " " +
            " ".join(shlex.quote(a) for a in remote)]
        procs.append((host, lo, hi, subprocess.Popen(cmd)))
    failed = []
    for host, lo, hi, p in procs:
        p.wait()
        if p.returncode != 0:
            failed.append((host, lo, hi, p.returncode))
    if failed:
        raise RuntimeError(
            f"pod generation failed on {failed}; re-run those slices "
            f"with `dist --range lo,hi` (chunks are deterministic)")
    # completeness check: every table produced something and no host
    # left an in-flight temp slice behind (small tables legitimately
    # emit fewer chunks than `parallel` — only child 1 writes them)
    tables = MAINTENANCE_TABLE_NAMES if args.update \
        else SOURCE_TABLE_NAMES
    empty = [t for t in tables
             if not os.path.isdir(os.path.join(data_dir, t)) or
             not os.listdir(os.path.join(data_dir, t))]
    stale = [d for d in os.listdir(data_dir) if d.startswith("_temp_")]
    if empty or stale:
        raise RuntimeError(
            f"pod generation incomplete: empty tables {empty[:5]}, "
            f"stale temp slices {stale[:5]}")


def _prepare_data_dir(path: str, overwrite: bool) -> str:
    """Create-or-guard the output dir (shared by local and pod modes);
    on overwrite, also clear stale _temp_* slices a killed prior run
    left behind (they would otherwise fail pod's completeness check)."""
    data_dir = get_abs_path(path)
    if not os.path.isdir(data_dir):
        os.makedirs(data_dir)
        return data_dir
    if get_dir_size(data_dir) > 0 and not overwrite:
        raise RuntimeError(
            f"There's already data in {data_dir}; "
            "use --overwrite_output to overwrite.")
    for d in os.listdir(data_dir):
        if d.startswith("_temp_"):
            shutil.rmtree(os.path.join(data_dir, d), ignore_errors=True)
    return data_dir


def generate_data(args) -> None:
    if args.type == "pod":
        generate_pod(args)
        return
    tool = check_build()
    range_start, range_end = 1, int(args.parallel)
    if args.range:
        range_start, range_end = valid_range(args.range, args.parallel)

    data_dir = get_abs_path(args.data_dir)
    target_dir = data_dir
    if args.range:
        # incremental generation goes to a temp dir, then merges up; a stale
        # temp dir from a failed prior run must not leak into the dataset
        # (reference guards both sides: nds_gen_data.py clean_temp_data).
        # The name carries the range so concurrent per-host slices of a
        # pod run cannot clobber each other's in-flight chunks.
        target_dir = os.path.join(data_dir,
                                  f"_temp_{range_start}_{range_end}_")
        shutil.rmtree(target_dir, ignore_errors=True)
        os.makedirs(target_dir)
    else:
        data_dir = _prepare_data_dir(args.data_dir,
                                     args.overwrite_output)
        target_dir = data_dir

    try:
        _fanout(args, range_start, range_end, target_dir, tool)
        _move_into_table_dirs(target_dir, range_start, range_end,
                              int(args.parallel), args.update)
        if args.range:
            _merge_temp_tables(target_dir, data_dir, args.update)
    except BaseException:
        if args.range:
            shutil.rmtree(target_dir, ignore_errors=True)
        raise


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        description="Generate NDS benchmark data (native seeded generator)")
    parser.add_argument("type", choices=["local", "dist", "pod"],
                        help="fan-out mode: local multiprocess; this "
                        "host's slice of a multi-host run (use --range); "
                        "or pod coordinator fanning slices out to "
                        "--hosts over a shared filesystem")
    parser.add_argument("scale", help="volume of data to generate in GB")
    parser.add_argument("parallel", type=parallel_value_type,
                        help="build data in <parallel_value> separate chunks")
    parser.add_argument("data_dir", help="generate data in this directory")
    parser.add_argument("--range",
                        help='incremental generation: which child chunks to '
                        'generate, "start,end" inclusive, within parallel')
    parser.add_argument("--overwrite_output", action="store_true",
                        help="overwrite existing data in the output path")
    parser.add_argument("--update", type=int,
                        help="generate refresh/update dataset <n> (one per "
                        "throughput stream)")
    parser.add_argument("--seed", type=int,
                        help="base RNG seed (default: generator built-in)")
    parser.add_argument("--hosts",
                        help="pod mode: comma-separated host list; child "
                        "chunks are split into contiguous per-host slices")
    parser.add_argument("--launcher", default="ssh -o BatchMode=yes {host}",
                        help="pod mode: launcher template; {host} is "
                        "substituted and the slice command is appended as "
                        "one shell argument (e.g. 'bash -c' to fan out "
                        "locally for testing)")
    parser.add_argument("--remote_python", default=sys.executable,
                        help="pod mode: python interpreter on the hosts")
    return parser


if __name__ == "__main__":
    generate_data(build_parser().parse_args())
