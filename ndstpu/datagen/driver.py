"""Data-generation driver CLI.

Mirrors the reference driver's interface and behaviors
(/root/reference/nds/nds_gen_data.py): local multiprocess fan-out of the
native generator, per-table subdirectory layout, incremental `--range`
generation merged from a temporary directory, `--update` refresh sets with
separate placement of the delete-date tables, and an overwrite guard.

The reference's `hdfs` mode (Hadoop MapReduce fan-out, GenTable.java) maps
here to `dist` mode: the same child-chunk fan-out executed on this host for
the host's slice of children — on a multi-host TPU pod each host runs the
driver with its own `--range`, no cluster scheduler needed (chunk content is
position-deterministic so any assignment of children to hosts is valid).
"""

from __future__ import annotations

import argparse
import os
import shutil
import subprocess

from ndstpu import schema
from ndstpu.check import (
    check_build,
    get_abs_path,
    get_dir_size,
    parallel_value_type,
    valid_range,
)

SOURCE_TABLE_NAMES = schema.SOURCE_TABLE_NAMES
MAINTENANCE_TABLE_NAMES = schema.MAINTENANCE_TABLE_NAMES


def _fanout(args, range_start: int, range_end: int, data_dir: str,
            tool: str) -> None:
    """Run one `ndsgen` process per child chunk, concurrently."""
    procs = []
    for child in range(range_start, range_end + 1):
        cmd = [
            str(tool),
            "-scale", str(args.scale),
            "-dir", data_dir,
            "-parallel", str(args.parallel),
            "-child", str(child),
        ]
        if args.update:
            cmd += ["-update", str(args.update)]
        if args.seed is not None:
            cmd += ["-seed", str(args.seed)]
        procs.append(subprocess.Popen(cmd))
    for p in procs:
        p.wait()
        if p.returncode != 0:
            raise RuntimeError(f"ndsgen failed with return code {p.returncode}")


def _move_into_table_dirs(data_dir: str, range_start: int, range_end: int,
                          parallel: int, update: int | None) -> None:
    """Move `{table}_{child}_{parallel}.dat` chunks into per-table folders
    (reference: nds_gen_data.py:229-242)."""
    tables = MAINTENANCE_TABLE_NAMES if update else SOURCE_TABLE_NAMES
    for table in tables:
        tdir = os.path.join(data_dir, table)
        os.makedirs(tdir, exist_ok=True)
        for child in range(range_start, range_end + 1):
            src = os.path.join(data_dir, f"{table}_{child}_{parallel}.dat")
            if os.path.exists(src):
                # full destination path so a re-run with --overwrite_output
                # replaces existing chunks (os.rename semantics)
                shutil.move(src, os.path.join(tdir, os.path.basename(src)))


def _merge_temp_tables(temp_dir: str, parent_dir: str,
                       update: int | None) -> None:
    """Move a --range run's per-table content up into the parent data dir
    (reference: nds_gen_data.py:91-117)."""
    tables = MAINTENANCE_TABLE_NAMES if update else SOURCE_TABLE_NAMES
    for table in tables:
        src_dir = os.path.join(temp_dir, table)
        if not os.path.isdir(src_dir):
            continue
        dst_dir = os.path.join(parent_dir, table)
        os.makedirs(dst_dir, exist_ok=True)
        for f in os.listdir(src_dir):
            shutil.move(os.path.join(src_dir, f), os.path.join(dst_dir, f))
    shutil.rmtree(temp_dir, ignore_errors=True)


def generate_data(args) -> None:
    tool = check_build()
    range_start, range_end = 1, int(args.parallel)
    if args.range:
        range_start, range_end = valid_range(args.range, args.parallel)

    data_dir = get_abs_path(args.data_dir)
    target_dir = data_dir
    if args.range:
        # incremental generation goes to a temp dir, then merges up; a stale
        # temp dir from a failed prior run must not leak into the dataset
        # (reference guards both sides: nds_gen_data.py clean_temp_data)
        target_dir = os.path.join(data_dir, "_temp_")
        shutil.rmtree(target_dir, ignore_errors=True)
        os.makedirs(target_dir)
    else:
        if not os.path.isdir(data_dir):
            os.makedirs(data_dir)
        elif get_dir_size(data_dir) > 0 and not args.overwrite_output:
            raise RuntimeError(
                f"There's already data in {data_dir}; "
                "use --overwrite_output to overwrite."
            )

    try:
        _fanout(args, range_start, range_end, target_dir, tool)
        _move_into_table_dirs(target_dir, range_start, range_end,
                              int(args.parallel), args.update)
        if args.range:
            _merge_temp_tables(target_dir, data_dir, args.update)
    except BaseException:
        if args.range:
            shutil.rmtree(target_dir, ignore_errors=True)
        raise


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        description="Generate NDS benchmark data (native seeded generator)")
    parser.add_argument("type", choices=["local", "dist"],
                        help="fan-out mode: local multiprocess, or this "
                        "host's slice of a multi-host run (use --range)")
    parser.add_argument("scale", help="volume of data to generate in GB")
    parser.add_argument("parallel", type=parallel_value_type,
                        help="build data in <parallel_value> separate chunks")
    parser.add_argument("data_dir", help="generate data in this directory")
    parser.add_argument("--range",
                        help='incremental generation: which child chunks to '
                        'generate, "start,end" inclusive, within parallel')
    parser.add_argument("--overwrite_output", action="store_true",
                        help="overwrite existing data in the output path")
    parser.add_argument("--update", type=int,
                        help="generate refresh/update dataset <n> (one per "
                        "throughput stream)")
    parser.add_argument("--seed", type=int,
                        help="base RNG seed (default: generator built-in)")
    return parser


if __name__ == "__main__":
    generate_data(build_parser().parse_args())
