// GENERATED from dists.json by ndstpu.check.render_dists_header
// -- do not edit; edit dists.json.
#pragma once
struct DistEntry { const char* v; int w; };
struct DistTable { const DistEntry* e; int n; int total; };
static const DistEntry kDist_fips_county_e[] = {{"Williamson County", 100}, {"Walker County", 80}, {"Ziebach County", 60}, {"Daviess County", 45}, {"Barrow County", 35}, {"Franklin Parish", 28}, {"Luce County", 22}, {"Richland County", 18}, {"Furnas County", 14}, {"Maverick County", 11}, {"Pennington County", 9}, {"Bronx County", 7}, {"Jackson County", 6}, {"Mesa County", 5}, {"Dauphin County", 4}, {"Levy County", 3}, {"Coal County", 3}, {"Mobile County", 2}, {"San Miguel County", 2}, {"Perry County", 1}};
static const DistTable kDist_fips_county = {kDist_fips_county_e, 20, 455};
static const DistEntry kDist_categories_e[] = {{"Women", 18}, {"Men", 15}, {"Children", 12}, {"Shoes", 10}, {"Music", 10}, {"Jewelry", 8}, {"Home", 8}, {"Sports", 7}, {"Books", 6}, {"Electronics", 6}};
static const DistTable kDist_categories = {kDist_categories_e, 10, 100};
static const DistEntry kDist_classes_e[] = {{"accent", 4}, {"bathroom", 4}, {"bedding", 5}, {"classical", 3}, {"country", 3}, {"dresses", 6}, {"fragrances", 4}, {"infants", 4}, {"maternity", 4}, {"pants", 6}, {"pop", 4}, {"rock", 3}, {"shirts", 6}, {"swimwear", 3}, {"athletic", 5}, {"casual", 5}, {"formal", 4}, {"mens watch", 2}, {"womens watch", 2}, {"computers", 4}, {"cameras", 3}, {"televisions", 3}, {"football", 3}, {"baseball", 3}, {"basketball", 3}, {"fiction", 4}, {"history", 3}, {"romance", 3}, {"self-help", 2}, {"travel", 2}};
static const DistTable kDist_classes = {kDist_classes_e, 30, 110};
static const DistEntry kDist_colors_e[] = {{"red", 12}, {"blue", 12}, {"green", 10}, {"yellow", 8}, {"purple", 7}, {"orange", 7}, {"black", 10}, {"white", 10}, {"pink", 6}, {"brown", 6}, {"gray", 5}, {"cyan", 3}, {"magenta", 3}, {"ivory", 4}, {"khaki", 4}, {"lavender", 4}, {"maroon", 4}, {"navy", 5}, {"olive", 4}, {"salmon", 4}, {"tan", 4}, {"teal", 4}, {"turquoise", 3}, {"violet", 3}, {"beige", 4}, {"azure", 2}, {"chartreuse", 2}, {"coral", 3}, {"crimson", 3}, {"gold", 4}, {"silver", 4}, {"plum", 2}, {"orchid", 2}, {"peach", 3}, {"mint", 2}, {"rose", 3}, {"ghost", 1}, {"snow", 2}, {"seashell", 1}, {"linen", 1}};
static const DistTable kDist_colors = {kDist_colors_e, 40, 181};
static const DistEntry kDist_states_e[] = {{"AL", 10}, {"AK", 2}, {"AZ", 9}, {"AR", 6}, {"CA", 35}, {"CO", 10}, {"CT", 6}, {"DE", 2}, {"FL", 25}, {"GA", 15}, {"HI", 2}, {"ID", 3}, {"IL", 20}, {"IN", 12}, {"IA", 7}, {"KS", 6}, {"KY", 8}, {"LA", 8}, {"ME", 3}, {"MD", 8}, {"MA", 10}, {"MI", 15}, {"MN", 9}, {"MS", 6}, {"MO", 11}, {"MT", 2}, {"NE", 4}, {"NV", 4}, {"NH", 2}, {"NJ", 12}, {"NM", 4}, {"NY", 28}, {"NC", 14}, {"ND", 2}, {"OH", 18}, {"OK", 7}, {"OR", 7}, {"PA", 19}, {"RI", 2}, {"SC", 8}, {"SD", 2}, {"TN", 11}, {"TX", 30}, {"UT", 5}, {"VT", 2}, {"VA", 12}, {"WA", 11}, {"WV", 4}, {"WI", 10}, {"WY", 2}};
static const DistTable kDist_states = {kDist_states_e, 50, 470};
static const DistEntry kDist_cities_e[] = {{"Midway", 40}, {"Fairview", 35}, {"Oakland", 20}, {"Springdale", 15}, {"Salem", 12}, {"Georgetown", 10}, {"Ashland", 9}, {"Riverside", 8}, {"Greenville", 8}, {"Franklin", 7}, {"Clinton", 6}, {"Marion", 6}, {"Bethel", 5}, {"Oakdale", 5}, {"Union", 5}, {"Wilson", 4}, {"Glendale", 4}, {"Centerville", 4}, {"Hopewell", 3}, {"Lakeview", 3}, {"Pleasant Hill", 3}, {"Mount Olive", 3}, {"Shiloh", 2}, {"Five Points", 2}, {"Oak Grove", 2}, {"Newport", 2}, {"Woodville", 2}, {"Concord", 2}, {"Antioch", 1}, {"Friendship", 1}};
static const DistTable kDist_cities = {kDist_cities_e, 30, 229};
static const DistEntry kDist_store_cities_e[] = {{"Midway", 40}, {"Fairview", 35}, {"Oakland", 12}, {"Springdale", 6}, {"Salem", 4}, {"Georgetown", 3}};
static const DistTable kDist_store_cities = {kDist_store_cities_e, 6, 100};
static const DistEntry kDist_store_states_e[] = {{"TN", 30}, {"GA", 20}, {"TX", 15}, {"CA", 10}, {"OH", 8}, {"IL", 7}, {"NY", 6}, {"FL", 4}};
static const DistTable kDist_store_states = {kDist_store_states_e, 8, 100};
static const DistEntry kDist_store_gmt_e[] = {{"-5", 60}, {"-6", 40}};
static const DistTable kDist_store_gmt = {kDist_store_gmt_e, 2, 100};
static const DistEntry kDist_gmt_offset_e[] = {{"-5", 35}, {"-6", 30}, {"-7", 12}, {"-8", 15}, {"-9", 4}, {"-10", 4}};
static const DistTable kDist_gmt_offset = {kDist_gmt_offset_e, 6, 100};
static const DistEntry kDist_education_e[] = {{"Primary", 12}, {"Secondary", 18}, {"College", 20}, {"2 yr Degree", 14}, {"4 yr Degree", 18}, {"Advanced Degree", 10}, {"Unknown", 8}};
static const DistTable kDist_education = {kDist_education_e, 7, 100};
static const DistEntry kDist_marital_status_e[] = {{"M", 30}, {"S", 28}, {"D", 18}, {"W", 12}, {"U", 12}};
static const DistTable kDist_marital_status = {kDist_marital_status_e, 5, 100};
static const DistEntry kDist_gender_e[] = {{"M", 50}, {"F", 50}};
static const DistTable kDist_gender = {kDist_gender_e, 2, 100};
static const DistEntry kDist_buy_potential_e[] = {{"0-500", 18}, {"501-1000", 16}, {"1001-5000", 22}, {"5001-10000", 16}, {">10000", 14}, {"Unknown", 14}};
static const DistTable kDist_buy_potential = {kDist_buy_potential_e, 6, 100};
static const DistEntry kDist_carriers_e[] = {{"UPS", 1}, {"FEDEX", 1}, {"AIRBORNE", 1}, {"USPS", 1}, {"DHL", 1}, {"TBS", 1}, {"ZHOU", 1}, {"GREAT EASTERN", 1}, {"DIAMOND", 1}, {"RUPEKSA", 1}, {"ORIENTAL", 1}, {"BOXBUNDLES", 1}, {"ALLIANCE", 1}, {"GERMA", 1}, {"HARMSTORF", 1}, {"PRIVATECARRIER", 1}, {"MSC", 1}, {"LATVIAN", 1}, {"ZOUROS", 1}, {"GLOBAL", 1}};
static const DistTable kDist_carriers = {kDist_carriers_e, 20, 20};
static const DistEntry kDist_reasons_e[] = {{"Package was damaged", 1}, {"Stopped working", 1}, {"Did not get it on time", 1}, {"Not the product that was ordred", 1}, {"Parts missing", 1}, {"Does not work with a product that I have", 1}, {"Gift exchange", 1}, {"Did not like the color", 1}, {"Did not like the model", 1}, {"Did not like the make", 1}, {"Did not like the warranty", 1}, {"No service location in my area", 1}, {"Found a better price in a store", 1}, {"Found a better extended warranty", 1}, {"reason 15", 1}, {"reason 16", 1}, {"reason 17", 1}, {"reason 18", 1}, {"reason 19", 1}, {"reason 20", 1}, {"reason 21", 1}, {"reason 22", 1}, {"reason 23", 1}, {"reason 24", 1}, {"reason 25", 1}, {"reason 26", 1}, {"reason 27", 1}, {"reason 28", 1}, {"reason 29", 1}, {"reason 30", 1}, {"reason 31", 1}, {"reason 32", 1}, {"reason 33", 1}, {"reason 34", 1}, {"reason 35", 1}};
static const DistTable kDist_reasons = {kDist_reasons_e, 35, 35};
