"""Seeded, chunk-parallel data generation (native C++ engine + Python driver).

The native tool `ndsgen.cpp` replaces the reference's tpcds-gen/dsdgen layer
(/root/reference/nds/tpcds-gen/, nds_gen_data.py) with a from-scratch,
counter-based-RNG generator whose output is byte-identical under any
`-parallel/-child` chunking.
"""

