// ndsgen — seeded, chunk-parallel decision-support data generator.
//
// TPU-native replacement for the reference's native generation engine
// (tpcds-gen/dsdgen wrapper, see /root/reference/nds/tpcds-gen/ and
// nds_gen_data.py).  Unlike dsdgen this is a from-scratch generator: it
// produces a TPC-DS-*shaped* dataset (same 25 tables, same columns, same
// referential structure, same pipe-delimited .dat output contract and
// `{table}_{child}_{parallel}.dat` chunk naming) from a counter-based RNG,
// so that any chunking of the work produces byte-identical global content:
// the value stream of row r of table t depends only on (seed, t, r).
//
// CLI (dsdgen-compatible surface, cf. nds_gen_data.py:211-225):
//   ndsgen -scale <SF> -dir <outdir> [-parallel <N> -child <i>]
//          [-table <name>] [-update <k>] [-seed <s>]
//
//   -parallel/-child: generate only chunk i of N (1-based), all tables.
//   -update k: generate the k-th refresh set (s_* staging tables + the
//              delete/inventory_delete date-range tables).
//
// Money columns are written with 2 decimal places; NULL is an empty field;
// lines end with a trailing '|' exactly like dsdgen output.

#include <cinttypes>
#include <cmath>
#include <cstdarg>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

// ---------------------------------------------------------------------------
// Counter-based RNG
// ---------------------------------------------------------------------------

static inline uint64_t splitmix64(uint64_t x) {
  x += 0x9E3779B97F4A7C15ULL;
  x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ULL;
  x = (x ^ (x >> 27)) * 0x94D049BB133111EBULL;
  return x ^ (x >> 31);
}

struct Rng {
  uint64_t key;
  uint64_t ctr = 0;
  explicit Rng(uint64_t seed, uint64_t table_id, uint64_t row) {
    key = splitmix64(seed ^ (table_id * 0xA24BAED4963EE407ULL) ^
                     (row * 0x9FB21C651E98DF25ULL));
  }
  uint64_t next() { return splitmix64(key + (ctr++) * 0x632BE59BD9B4E019ULL); }
  // uniform in [lo, hi] inclusive
  int64_t range(int64_t lo, int64_t hi) {
    return lo + (int64_t)(next() % (uint64_t)(hi - lo + 1));
  }
  bool chance(double p) { return (next() >> 11) * 0x1.0p-53 < p; }
  // money in cents, uniform [lo_cents, hi_cents]
  int64_t cents(int64_t lo, int64_t hi) { return range(lo, hi); }
  // Zipf(s~1)-skewed pick in [1, n]: rank = floor(n^u) gives
  // P(rank <= k) = ln(k+1)/ln(n+1) — a handful of hot keys carry most
  // of the mass, like dsdgen's weighted distribution tables give real
  // NDS data (reference nds/tpcds-gen; uniform draws made every
  // selectivity and every join fan-out unrealistically flat).  The
  // rank is scattered over the key space by a coprime multiplier so
  // hot keys are spread out, not clustered at 1..k.  One next() call —
  // counter-stream stability for the re-derivation in gen_return.
  int64_t zipf(int64_t n) {
    if (n <= 1) return 1;
    double u = (next() >> 11) * 0x1.0p-53;  // [0, 1)
    double rf = exp(u * log((double)n + 1.0));
    int64_t rank = (int64_t)rf;  // 1..n
    if (rank < 1) rank = 1;
    if (rank > n) rank = n;
    static const uint64_t kScatter[] = {2654435761ULL, 1073741827ULL,
                                        805306457ULL, 100000007ULL};
    for (uint64_t p : kScatter) {
      uint64_t a = p % (uint64_t)n, b = (uint64_t)n;  // gcd(p, n) == 1?
      while (a) { uint64_t t = b % a; b = a; a = t; }
      if (b == 1) return (int64_t)(((uint64_t)(rank - 1) * p) % (uint64_t)n) + 1;
    }
    return rank;  // no coprime scatter (tiny n): unscattered rank
  }
};

// ---------------------------------------------------------------------------
// Calendar helpers (days <-> civil date; Julian day numbering like TPC-DS
// date_sk).  JD 2440588 == 1970-01-01.
// ---------------------------------------------------------------------------

static const int64_t JD_EPOCH_1970 = 2440588;
static const int64_t DATE_DIM_FIRST_JD = 2415022;  // 1900-01-02
static const int64_t DATE_DIM_ROWS = 73049;        // through 2100-01-01
static const int64_t SALES_FIRST_JD = 2450816;     // 1998-01-02
static const int64_t SALES_LAST_JD = 2452642;      // 2003-01-02

struct Civil {
  int y, m, d;
};

static Civil civil_from_days(int64_t z) {  // days since 1970-01-01
  z += 719468;
  int64_t era = (z >= 0 ? z : z - 146096) / 146097;
  unsigned doe = (unsigned)(z - era * 146097);
  unsigned yoe = (doe - doe / 1460 + doe / 36524 - doe / 146096) / 365;
  int64_t y = (int64_t)yoe + era * 400;
  unsigned doy = doe - (365 * yoe + yoe / 4 - yoe / 100);
  unsigned mp = (5 * doy + 2) / 153;
  unsigned d = doy - (153 * mp + 2) / 5 + 1;
  unsigned m = mp < 10 ? mp + 3 : mp - 9;
  return Civil{(int)(y + (m <= 2)), (int)m, (int)d};
}

static int64_t days_from_civil(int y, unsigned m, unsigned d) {
  y -= m <= 2;
  int64_t era = (y >= 0 ? y : y - 399) / 400;
  unsigned yoe = (unsigned)(y - era * 400);
  unsigned doy = (153 * (m > 2 ? m - 3 : m + 9) + 2) / 5 + d - 1;
  unsigned doe = yoe * 365 + yoe / 4 - yoe / 100 + doy;
  return era * 146097 + (int64_t)doe - 719468;
}

static int weekday(int64_t days) {  // 0=Sunday (TPC d_dow: 0=Sunday)
  return (int)(((days + 4) % 7 + 7) % 7);
}

// ---------------------------------------------------------------------------
// Output writer
// ---------------------------------------------------------------------------

struct Writer {
  FILE* f;
  char buf[1 << 16];
  explicit Writer(const std::string& path) {
    f = fopen(path.c_str(), "w");
    if (!f) {
      fprintf(stderr, "ndsgen: cannot open %s\n", path.c_str());
      exit(2);
    }
    setvbuf(f, buf, _IOFBF, sizeof(buf));
  }
  ~Writer() {
    if (ferror(f) || fclose(f) != 0) {
      fprintf(stderr, "ndsgen: write error (disk full?)\n");
      exit(3);
    }
  }
  void fint(int64_t v) { fprintf(f, "%" PRId64 "|", v); }
  void fnull() { fputc('|', f); }
  void fstr(const char* s) { fprintf(f, "%s|", s); }
  void fstr(const std::string& s) { fprintf(f, "%s|", s.c_str()); }
  void fmoney(int64_t c) {  // cents -> d.cc
    if (c < 0)
      fprintf(f, "-%" PRId64 ".%02d|", (-c) / 100, (int)((-c) % 100));
    else
      fprintf(f, "%" PRId64 ".%02d|", c / 100, (int)(c % 100));
  }
  void fdate(int64_t jd) {
    Civil c = civil_from_days(jd - JD_EPOCH_1970);
    fprintf(f, "%04d-%02d-%02d|", c.y, c.m, c.d);
  }
  void endrow() { fputc('\n', f); }
};

// ---------------------------------------------------------------------------
// Word pools
// ---------------------------------------------------------------------------

static const char* kStreetNames[] = {"Main", "Oak", "Park", "First", "Elm",
    "Second", "Washington", "Maple", "Cedar", "Pine", "Lake", "Hill", "Walnut",
    "Spring", "North", "Ridge", "Church", "Willow", "Mill", "Sunset", "Railroad",
    "Jackson", "River", "Highland", "Johnson", "View", "Forest", "Green",
    "Meadow", "Broad", "Chestnut", "Franklin", "College", "Smith", "Center",
    "Davis", "Wilson", "Birch", "Locust", "Dogwood"};
static const char* kStreetTypes[] = {"Street", "Avenue", "Boulevard", "Drive",
    "Lane", "Road", "Court", "Circle", "Way", "Parkway", "Pkwy", "Blvd", "Ave",
    "Dr", "Ln", "RD", "Ct", "Cir", "ST", "Wy"};
static const char* kCountries[] = {"United States"};
static const char* kLocationTypes[] = {"apartment", "condo", "single family"};
static const char* kFirstNames[] = {"James", "Mary", "John", "Patricia",
    "Robert", "Jennifer", "Michael", "Linda", "William", "Elizabeth", "David",
    "Barbara", "Richard", "Susan", "Joseph", "Jessica", "Thomas", "Sarah",
    "Charles", "Karen", "Christopher", "Nancy", "Daniel", "Lisa", "Matthew",
    "Margaret", "Anthony", "Betty", "Donald", "Sandra", "Mark", "Ashley",
    "Paul", "Dorothy", "Steven", "Kimberly", "Andrew", "Emily", "Kenneth",
    "Donna", "Jose", "Michelle", "Edward", "Carol", "Brian", "Amanda",
    "George", "Melissa", "Ronald", "Deborah"};
static const char* kLastNames[] = {"Smith", "Johnson", "Williams", "Brown",
    "Jones", "Garcia", "Miller", "Davis", "Rodriguez", "Martinez", "Hernandez",
    "Lopez", "Gonzalez", "Wilson", "Anderson", "Thomas", "Taylor", "Moore",
    "Jackson", "Martin", "Lee", "Perez", "Thompson", "White", "Harris",
    "Sanchez", "Clark", "Ramirez", "Lewis", "Robinson", "Walker", "Young",
    "Allen", "King", "Wright", "Scott", "Torres", "Nguyen", "Hill", "Flores"};
static const char* kSalutations[] = {"Mr.", "Mrs.", "Ms.", "Dr.", "Miss", "Sir"};
static const char* kCredit[] = {"Low Risk", "Good", "High Risk", "Unknown"};
static const char* kColors[] = {"red", "blue", "green", "yellow", "purple",
    "orange", "black", "white", "pink", "brown", "gray", "cyan", "magenta",
    "ivory", "khaki", "lavender", "maroon", "navy", "olive", "salmon", "tan",
    "teal", "turquoise", "violet", "beige", "azure", "chartreuse", "coral",
    "crimson", "gold", "silver", "plum", "orchid", "peach", "mint", "rose",
    "ghost", "snow", "seashell", "linen"};
static const char* kUnits[] = {"Each", "Dozen", "Case", "Pound", "Ounce",
    "Pallet", "Gross", "Box", "Carton", "Bundle", "Ton", "Dram", "Cup",
    "Gram", "Lb", "Oz", "Tbl", "Tsp", "Unknown", "N/A"};
static const char* kSizes[] = {"small", "medium", "large", "extra large",
    "economy", "petite", "N/A"};
static const char* kContainers[] = {"Unknown"};
static const char* kHours[] = {"8AM-4PM", "8AM-8AM", "8AM-12AM"};
static const char* kShipTypes[] = {"EXPRESS", "NEXT DAY", "OVERNIGHT",
    "REGULAR", "TWO DAY", "LIBRARY"};
static const char* kShipCodes[] = {"AIR", "SURFACE", "SEA"};
static const char* kShifts[] = {"first", "second", "third"};
static const char* kWordPool[] = {"results", "important", "whole", "right",
    "general", "great", "special", "large", "social", "economic", "national",
    "young", "early", "possible", "different", "small", "major", "final",
    "international", "full", "public", "available", "local", "sure", "low",
    "necessary", "true", "significant", "recent", "certain", "military",
    "central", "similar", "main", "individual", "political", "common", "strong",
    "easy", "clear", "single", "hard", "good", "new", "old", "high", "long",
    "little", "own", "other"};

template <size_t N>
static const char* pick(Rng& r, const char* const (&pool)[N]) {
  return pool[r.next() % N];
}

// Shared weighted distribution tables — generated from dists.json at
// build time (ndstpu.check.render_dists_header).  The SAME tables feed
// dsqgen-style template-parameter draws in streamgen.py, the analog of
// dsdgen and dsqgen reading the same .dst files (reference
// nds/tpcds-gen/patches/templates.patch `distmember(fips_county,...)`):
// predicates rendered into queries land on value domains the generated
// data actually has, with realistic non-uniform selectivity.
#include "dists_gen.h"

static int dpick_idx(Rng& r, const DistTable& t) {
  int64_t x = r.range(0, t.total - 1);
  for (int i = 0; i < t.n; i++) {
    x -= t.e[i].w;
    if (x < 0) return i;
  }
  return 0;
}
static const char* dpick(Rng& r, const DistTable& t) {
  return t.e[dpick_idx(r, t)].v;
}
// gmt-offset tables carry string values ("-5"); columns store ints
static int64_t dpick_int(Rng& r, const DistTable& t) {
  return atoll(dpick(r, t));
}

static std::string sentence(Rng& r, int nwords) {
  std::string s;
  for (int i = 0; i < nwords; i++) {
    if (i) s += ' ';
    s += kWordPool[r.next() % (sizeof(kWordPool) / sizeof(kWordPool[0]))];
  }
  return s;
}

// 16-char business key, unique per sk: "AAAA..." base-26 suffix of sk.
static std::string bkey(int64_t sk) {
  char b[17];
  memset(b, 'A', 16);
  b[16] = 0;
  uint64_t v = (uint64_t)sk;
  for (int i = 15; i >= 0 && v; i--) {
    b[i] = (char)('A' + (v % 26));
    v /= 26;
  }
  return std::string(b);
}

// ---------------------------------------------------------------------------
// Scaling model.  SF == gigabytes, like dsdgen -scale.  Row counts follow
// the published TPC-DS row-count step table (spec Table 3-2) at the step
// scale factors 1/10/100/1000 — the same table dsdgen's -scale implements
// (the reference wraps dsdgen at nds/tpcds-gen/src/main/java/org/notmysock/
// tpcds/GenTable.java:49-167).  The step table is NOT a smooth curve:
// item jumps 18,000 -> 102,000 at SF10, customer 100,000 -> 500,000,
// web_site is even non-monotonic (42 at SF10, 24 at SF100) — a lin/sqrt
// heuristic silently changes the workload above SF1.
// Between steps: facts interpolate linearly in SF, dims geometrically
// (log-scale across each decade); below SF1 both shrink from the SF1
// anchor (facts linear, dims damped) so tiny test datasets keep their
// proportions; above SF1000 the last segment extrapolates.
// ---------------------------------------------------------------------------

struct Sizes {
  double sf;
  int64_t store_sales, catalog_sales, web_sales;
  int64_t store_returns, catalog_returns, web_returns;
  int64_t inventory, inv_weeks;
  int64_t customer, customer_address, customer_demographics;
  int64_t household_demographics, income_band;
  int64_t item, store, warehouse, web_site, web_page, promotion, catalog_page;
  int64_t call_center, ship_mode, reason, time_dim, date_dim;
};

static int64_t lin(double sf, int64_t base) {
  int64_t v = (int64_t)llround(base * sf);
  return v < 1 ? 1 : v;
}

// one table's published row counts at SF 1 / 10 / 100 / 1000
struct Steps {
  int64_t s1, s10, s100, s1000;
};

static int64_t step_count(double sf, const Steps& t, bool fact) {
  if (sf < 1.0) {
    double f = fact ? sf : (0.1 + 0.9 * sf);
    int64_t v = (int64_t)llround((double)t.s1 * f);
    return v < 1 ? 1 : v;
  }
  const double xs[4] = {1.0, 10.0, 100.0, 1000.0};
  const double ys[4] = {(double)t.s1, (double)t.s10, (double)t.s100,
                        (double)t.s1000};
  if (sf >= 1000.0) {
    double v = fact ? ys[3] * (sf / 1000.0)
                    : ys[3] * pow(ys[3] / ys[2], log10(sf / 1000.0));
    return (int64_t)llround(v);
  }
  int i = sf < 10.0 ? 0 : (sf < 100.0 ? 1 : 2);
  double v;
  if (sf == xs[i]) {
    v = ys[i];
  } else if (fact) {
    double w = (sf - xs[i]) / (xs[i + 1] - xs[i]);
    v = ys[i] + w * (ys[i + 1] - ys[i]);
  } else {
    double w = log10(sf / xs[i]);  // 0..1 across the decade
    v = ys[i] * pow(ys[i + 1] / ys[i], w);
  }
  int64_t r = (int64_t)llround(v);
  return r < 1 ? 1 : r;
}

static Sizes compute_sizes(double sf) {
  // TPC-DS spec Table 3-2 row counts, columns SF1 / SF10 / SF100 / SF1000
  static const Steps kStoreSales = {2880404, 28800991, 287997024,
                                    2879987999};
  static const Steps kCatalogSales = {1441548, 14401261, 143997065,
                                      1439980416};
  static const Steps kWebSales = {719384, 7197566, 72001237, 720000376};
  static const Steps kStoreReturns = {287514, 2875432, 28795080,
                                      287999764};
  static const Steps kCatalogReturns = {144067, 1439749, 14404374,
                                        143996756};
  static const Steps kWebReturns = {71763, 719217, 7197670, 71997522};
  static const Steps kItem = {18000, 102000, 204000, 300000};
  static const Steps kCustomer = {100000, 500000, 2000000, 12000000};
  static const Steps kCustomerAddress = {50000, 250000, 1000000, 6000000};
  static const Steps kStore = {12, 102, 402, 1002};
  static const Steps kWarehouse = {5, 10, 15, 20};
  static const Steps kWebPage = {60, 200, 2040, 3000};
  static const Steps kPromotion = {300, 500, 1000, 1500};
  static const Steps kCallCenter = {6, 24, 30, 42};
  static const Steps kWebSite = {30, 42, 24, 54};
  static const Steps kCatalogPage = {11718, 12000, 20400, 30000};
  static const Steps kReason = {35, 45, 55, 65};
  Sizes z;
  z.sf = sf;
  z.store_sales = step_count(sf, kStoreSales, true);
  z.catalog_sales = step_count(sf, kCatalogSales, true);
  z.web_sales = step_count(sf, kWebSales, true);
  z.store_returns = step_count(sf, kStoreReturns, true);
  z.catalog_returns = step_count(sf, kCatalogReturns, true);
  z.web_returns = step_count(sf, kWebReturns, true);
  z.item = step_count(sf, kItem, false);
  z.warehouse = step_count(sf, kWarehouse, false);
  z.inv_weeks = 261;  // weekly snapshots over the 5-year window
  // inventory == weeks x (item/2) x warehouse; at the step SFs this
  // reproduces the published counts exactly (e.g. 261*51,000*10 =
  // 133,110,000 at SF10) and stays consistent with item/warehouse
  // in between
  z.inventory = z.inv_weeks * (z.item / 2 < 1 ? 1 : z.item / 2) * z.warehouse;
  z.customer = step_count(sf, kCustomer, false);
  z.customer_address = step_count(sf, kCustomerAddress, false);
  // full cross product of the demographic attributes — derived from
  // the SHARED dist tables so a dists.json edit cannot silently
  // truncate coverage (gender x marital x education x 20 purchase
  // estimates x 4 credit ratings x 7^3 dependent counts = 1,920,800
  // at the spec sizes, locked by test_spec_step_table_cardinalities)
  z.customer_demographics = (int64_t)kDist_gender.n *
      kDist_marital_status.n * kDist_education.n * 20 * 4 * 7 * 7 * 7;
  z.household_demographics = 7200;
  z.income_band = 20;
  z.store = step_count(sf, kStore, false);
  z.web_site = step_count(sf, kWebSite, false);
  z.web_page = step_count(sf, kWebPage, false);
  z.promotion = step_count(sf, kPromotion, false);
  z.catalog_page = step_count(sf, kCatalogPage, false);
  z.call_center = step_count(sf, kCallCenter, false);
  z.ship_mode = 20;
  z.reason = step_count(sf, kReason, false);
  z.time_dim = 86400;
  z.date_dim = DATE_DIM_ROWS;
  return z;
}

// table ids for RNG keying — order must stay stable forever.
enum TableId {
  T_CUSTOMER_ADDRESS = 1, T_CUSTOMER_DEMOGRAPHICS, T_DATE_DIM, T_WAREHOUSE,
  T_SHIP_MODE, T_TIME_DIM, T_REASON, T_INCOME_BAND, T_ITEM, T_STORE,
  T_CALL_CENTER, T_CUSTOMER, T_WEB_SITE, T_STORE_RETURNS,
  T_HOUSEHOLD_DEMOGRAPHICS, T_WEB_PAGE, T_PROMOTION, T_CATALOG_PAGE,
  T_INVENTORY, T_CATALOG_RETURNS, T_WEB_RETURNS, T_WEB_SALES,
  T_CATALOG_SALES, T_STORE_SALES, T_DBGEN_VERSION,
  // staging tables for -update
  T_S_PURCHASE = 40, T_S_PURCHASE_LINEITEM, T_S_CATALOG_ORDER,
  T_S_CATALOG_ORDER_LINEITEM, T_S_WEB_ORDER, T_S_WEB_ORDER_LINEITEM,
  T_S_STORE_RETURNS, T_S_CATALOG_RETURNS, T_S_WEB_RETURNS, T_S_INVENTORY,
  T_DELETE = 60, T_INVENTORY_DELETE,
};

static uint64_t g_seed = 19620718;  // default base seed
static Sizes g_sz;

// chunk [begin, end) of n rows for child i of p
static void chunk(int64_t n, int p, int c, int64_t* b, int64_t* e) {
  int64_t per = n / p, rem = n % p;
  *b = (int64_t)(c - 1) * per + (c - 1 < rem ? c - 1 : rem);
  *e = *b + per + (c - 1 < rem ? 1 : 0);
}

// ---------------------------------------------------------------------------
// Sales row models.  Returns tables re-derive their parent sale's values by
// reconstructing the same Rng, giving exact referential integrity without
// storing anything.
// ---------------------------------------------------------------------------

struct SaleCore {
  int64_t date_sk, time_sk, item_sk, customer_sk, cdemo_sk, hdemo_sk, addr_sk;
  int64_t channel_sk;   // store_sk / call_center-ish / web_site_sk
  int64_t promo_sk, ticket;  // ticket or order number
  int64_t quantity;
  int64_t wholesale, list, sales;  // cents per unit
  int64_t ext_discount, ext_sales, ext_wholesale, ext_list, ext_tax, coupon;
  int64_t net_paid, net_paid_tax, net_profit;
  bool null_date, null_customer, null_channel, null_promo;
};

// items per ticket (avg ~3) — ticket id = row / spread
static const int TICKET_SPREAD = 3;

static SaleCore gen_sale(uint64_t table_id, int64_t row, int64_t n_channel,
                         int64_t order_spread) {
  Rng r(g_seed, table_id, row);
  SaleCore s;
  s.null_date = r.chance(0.02);
  s.date_sk = r.range(SALES_FIRST_JD, SALES_LAST_JD);
  // holiday-season date skew: ~30% of sales land in Nov/Dec (dsdgen
  // concentrates sales around the holidays the same way; uniform dates
  // starved the date-partition pruning and Q-over-December queries of
  // realistic selectivity).  Both draws always happen — the counter
  // stream must not depend on the branch (returns re-derive the sale).
  bool holiday = r.chance(0.30);
  int64_t hol_off = r.range(0, 60);
  if (holiday) {
    Civil c = civil_from_days(s.date_sk - JD_EPOCH_1970);
    int y = c.y > 2002 ? 2002 : c.y;  // Nov 2003 exceeds the window
    s.date_sk = days_from_civil(y, 11, 1) + JD_EPOCH_1970 + hol_off;
  }
  s.time_sk = r.range(0, 86399);
  s.item_sk = r.zipf(g_sz.item);
  s.null_customer = r.chance(0.03);
  s.customer_sk = r.zipf(g_sz.customer);
  s.cdemo_sk = r.range(1, g_sz.customer_demographics);
  s.hdemo_sk = r.range(1, g_sz.household_demographics);
  s.addr_sk = r.range(1, g_sz.customer_address);
  s.null_channel = r.chance(0.02);
  s.channel_sk = r.range(1, n_channel);
  s.null_promo = r.chance(0.5);
  s.promo_sk = r.range(1, g_sz.promotion);
  s.ticket = row / order_spread + 1;
  s.quantity = r.range(1, 100);
  s.wholesale = r.cents(100, 10000);                     // 1.00 .. 100.00
  s.list = s.wholesale + r.cents(0, s.wholesale);        // markup <= 100%
  s.sales = (s.list * r.range(20, 100)) / 100;           // discount off list
  s.ext_sales = s.quantity * s.sales;
  s.ext_wholesale = s.quantity * s.wholesale;
  s.ext_list = s.quantity * s.list;
  s.ext_discount = s.ext_list - s.ext_sales;
  s.coupon = r.chance(0.15) ? r.cents(0, s.ext_sales / 2) : 0;
  s.ext_tax = ((s.ext_sales - s.coupon) * r.range(0, 9)) / 100;
  s.net_paid = s.ext_sales - s.coupon;
  s.net_paid_tax = s.net_paid + s.ext_tax;
  s.net_profit = s.net_paid - s.ext_wholesale;
  return s;
}

// deterministic "is row k of parent sales returned" mapping: return row j
// maps to parent sale row j * (parent_n / returns_n)-ish stride.
static int64_t return_parent_row(int64_t j, int64_t parent_n, int64_t ret_n) {
  if (ret_n <= 0) return 0;
  int64_t stride = parent_n / ret_n;
  if (stride < 1) stride = 1;
  return (j * stride) % parent_n;
}

struct RetCore {
  int64_t ret_date_sk, ret_time_sk, reason_sk, qty;
  int64_t amt, tax, amt_inc_tax, fee, ship_cost, refunded, reversed, credit,
      net_loss;
};

static RetCore gen_return(uint64_t table_id, int64_t row, const SaleCore& s) {
  Rng r(g_seed, table_id, row);
  RetCore t;
  t.ret_date_sk = s.date_sk + r.range(1, 90);
  if (t.ret_date_sk > SALES_LAST_JD + 90) t.ret_date_sk = SALES_LAST_JD + 90;
  t.ret_time_sk = r.range(0, 86399);
  t.reason_sk = r.range(1, g_sz.reason);
  t.qty = r.range(1, s.quantity);
  t.amt = t.qty * s.sales;
  t.tax = (t.amt * r.range(0, 9)) / 100;
  t.amt_inc_tax = t.amt + t.tax;
  t.fee = r.cents(50, 10000);
  t.ship_cost = r.cents(0, t.amt / 2 + 1);
  t.refunded = (t.amt * r.range(0, 100)) / 100;
  int64_t rest = t.amt - t.refunded;
  t.reversed = (rest * r.range(0, 100)) / 100;
  t.credit = rest - t.reversed;
  t.net_loss = t.fee + t.ship_cost + t.tax;
  return t;
}

// ---------------------------------------------------------------------------
// Dimension generators
// ---------------------------------------------------------------------------

static void gen_customer_address(Writer& w, int64_t b, int64_t e) {
  for (int64_t i = b; i < e; i++) {
    int64_t sk = i + 1;
    Rng r(g_seed, T_CUSTOMER_ADDRESS, i);
    w.fint(sk);
    w.fstr(bkey(sk));
    char num[16];
    snprintf(num, sizeof num, "%" PRId64, r.range(1, 999));
    w.fstr(num);
    {
      std::string sn = std::string(pick(r, kStreetNames));
      if (r.chance(0.3)) sn += std::string(" ") + pick(r, kStreetNames);
      w.fstr(sn);
    }
    w.fstr(pick(r, kStreetTypes));
    if (r.chance(0.85)) {
      char suite[16];
      snprintf(suite, sizeof suite, "Suite %" PRId64, r.range(0, 99));
      w.fstr(suite);
    } else
      w.fnull();
    w.fstr(dpick(r, kDist_cities));
    w.fstr(dpick(r, kDist_fips_county));
    w.fstr(dpick(r, kDist_states));
    char zip[8];
    snprintf(zip, sizeof zip, "%05" PRId64, r.range(601, 99950));
    w.fstr(zip);
    w.fstr(kCountries[0]);
    // gmt offset, weighted toward eastern/central like the population
    w.fmoney(100 * dpick_int(r, kDist_gmt_offset));
    w.fstr(pick(r, kLocationTypes));
    w.endrow();
  }
}

static void gen_customer_demographics(Writer& w, int64_t b, int64_t e) {
  // pure cross-product enumeration like TPC-DS: gender x marital x education
  // x purchase_estimate x credit x dep x dep_employed x dep_college
  for (int64_t i = b; i < e; i++) {
    int64_t sk = i + 1, v = i;
    int g = v % kDist_gender.n; v /= kDist_gender.n;
    int m = v % kDist_marital_status.n; v /= kDist_marital_status.n;
    int ed = v % kDist_education.n; v /= kDist_education.n;
    int pe = v % 20; v /= 20;
    int cr = v % 4; v /= 4;
    int dep = v % 7; v /= 7;
    int depe = v % 7; v /= 7;
    int depc = v % 7;
    w.fint(sk);
    w.fstr(kDist_gender.e[g].v);
    w.fstr(kDist_marital_status.e[m].v);
    w.fstr(kDist_education.e[ed].v);
    w.fint(500 * (pe + 1));
    w.fstr(kCredit[cr]);
    w.fint(dep);
    w.fint(depe);
    w.fint(depc);
    w.endrow();
  }
}

static const char* kDayNames[] = {"Sunday", "Monday", "Tuesday", "Wednesday",
    "Thursday", "Friday", "Saturday"};

static void gen_date_dim(Writer& w, int64_t b, int64_t e) {
  for (int64_t i = b; i < e; i++) {
    int64_t jd = DATE_DIM_FIRST_JD + i;
    int64_t days70 = jd - JD_EPOCH_1970;
    Civil c = civil_from_days(days70);
    int dow = weekday(days70);
    int64_t jan1 = days_from_civil(c.y, 1, 1);
    int doy = (int)(days70 - jan1) + 1;
    int qoy = (c.m - 1) / 3 + 1;
    int64_t month_seq = (int64_t)(c.y - 1900) * 12 + (c.m - 1);
    int64_t week_seq = (jd - DATE_DIM_FIRST_JD) / 7 + 1;
    int64_t quarter_seq = (int64_t)(c.y - 1900) * 4 + (qoy - 1);
    w.fint(jd);                    // d_date_sk
    w.fstr(bkey(jd));              // d_date_id
    w.fdate(jd);                   // d_date
    w.fint(month_seq);
    w.fint(week_seq);
    w.fint(quarter_seq);
    w.fint(c.y);
    w.fint(dow);
    w.fint(c.m);
    w.fint(c.d);
    w.fint(qoy);
    w.fint(c.y);                   // fiscal == calendar
    w.fint(quarter_seq);
    w.fint(week_seq);
    w.fstr(kDayNames[dow]);
    char qn[24];
    snprintf(qn, sizeof qn, "%04dQ%d", c.y, qoy);
    w.fstr(qn);
    w.fstr((c.m == 12 && c.d == 25) || (c.m == 1 && c.d == 1) || doy == 185 ? "Y"
                                                                            : "N");
    w.fstr(dow == 0 || dow == 6 ? "Y" : "N");
    w.fstr((c.m == 12 && c.d == 26) || (c.m == 1 && c.d == 2) ? "Y" : "N");
    int64_t first_dom = days_from_civil(c.y, c.m, 1) + JD_EPOCH_1970;
    int nm_y = c.m == 12 ? c.y + 1 : c.y;
    int nm_m = c.m == 12 ? 1 : c.m + 1;
    int64_t last_dom = days_from_civil(nm_y, nm_m, 1) + JD_EPOCH_1970 - 1;
    w.fint(first_dom);
    w.fint(last_dom);
    w.fint(jd - 365);  // same day last year
    w.fint(jd - 91);   // same day last quarter
    w.fstr("N");
    w.fstr("N");
    w.fstr("N");
    w.fstr("N");
    w.fstr("N");
    w.endrow();
  }
}

static void gen_time_dim(Writer& w, int64_t b, int64_t e) {
  for (int64_t i = b; i < e; i++) {
    int64_t sk = i;  // t_time_sk in [0, 86399]
    int h = (int)(i / 3600), mi = (int)((i / 60) % 60), s = (int)(i % 60);
    w.fint(sk);
    w.fstr(bkey(sk + 1));
    w.fint(i);
    w.fint(h);
    w.fint(mi);
    w.fint(s);
    w.fstr(h < 12 ? "AM" : "PM");
    w.fstr(kShifts[h / 8]);
    w.fstr(kShifts[(h / 4) % 3]);
    const char* meal = h >= 6 && h <= 9    ? "breakfast"
                       : h >= 11 && h <= 14 ? "lunch"
                       : h >= 17 && h <= 21 ? "dinner"
                                            : "";
    if (*meal)
      w.fstr(meal);
    else
      w.fnull();
    w.endrow();
  }
}

static void gen_warehouse(Writer& w, int64_t b, int64_t e) {
  for (int64_t i = b; i < e; i++) {
    int64_t sk = i + 1;
    Rng r(g_seed, T_WAREHOUSE, i);
    w.fint(sk);
    w.fstr(bkey(sk));
    w.fstr("Warehouse " + std::to_string(sk));
    w.fint(r.range(50000, 999999));
    char num[16];
    snprintf(num, sizeof num, "%" PRId64, r.range(1, 999));
    w.fstr(num);
    w.fstr(pick(r, kStreetNames));
    w.fstr(pick(r, kStreetTypes));
    char suite[16];
    snprintf(suite, sizeof suite, "Suite %" PRId64, r.range(0, 99));
    w.fstr(suite);
    w.fstr(dpick(r, kDist_cities));
    w.fstr(dpick(r, kDist_fips_county));
    w.fstr(dpick(r, kDist_states));
    char zip[8];
    snprintf(zip, sizeof zip, "%05" PRId64, r.range(601, 99950));
    w.fstr(zip);
    w.fstr(kCountries[0]);
    w.fmoney(-100 * r.range(5, 10));
    w.endrow();
  }
}

static void gen_ship_mode(Writer& w, int64_t b, int64_t e) {
  for (int64_t i = b; i < e; i++) {
    int64_t sk = i + 1;
    Rng r(g_seed, T_SHIP_MODE, i);
    w.fint(sk);
    w.fstr(bkey(sk));
    w.fstr(kShipTypes[i % 6]);
    w.fstr(kShipCodes[(i / 6) % 3]);
    w.fstr(kDist_carriers.e[i % kDist_carriers.n].v);
    char contract[24];
    snprintf(contract, sizeof contract, "%" PRId64, r.range(1000000, 9999999));
    w.fstr(contract);
    w.endrow();
  }
}

static void gen_reason(Writer& w, int64_t b, int64_t e) {
  for (int64_t i = b; i < e; i++) {
    int64_t sk = i + 1;
    w.fint(sk);
    w.fstr(bkey(sk));
    w.fstr(kDist_reasons.e[i % kDist_reasons.n].v);
    w.endrow();
  }
}

static void gen_income_band(Writer& w, int64_t b, int64_t e) {
  for (int64_t i = b; i < e; i++) {
    int64_t sk = i + 1;
    w.fint(sk);
    w.fint(i * 10000 + 1 - (i == 0));
    w.fint((i + 1) * 10000);
    w.endrow();
  }
}

static void gen_item(Writer& w, int64_t b, int64_t e) {
  for (int64_t i = b; i < e; i++) {
    int64_t sk = i + 1;
    Rng r(g_seed, T_ITEM, i);
    w.fint(sk);
    w.fstr(bkey(sk));
    w.fdate(SALES_FIRST_JD - (int64_t)r.range(0, 1000));  // rec start
    if (r.chance(0.25))
      w.fdate(SALES_LAST_JD + (int64_t)r.range(0, 200));
    else
      w.fnull();
    w.fstr(sentence(r, (int)r.range(5, 20)));
    int64_t price = r.cents(100, 10000);
    w.fmoney(price);
    w.fmoney((price * r.range(30, 90)) / 100);
    // weighted category/class: hot categories get more items, so
    // Zipf-hot item keys skew category aggregates realistically (the
    // dist indices also feed the brand-id encoding below)
    int cat = dpick_idx(r, kDist_categories);
    int cls = dpick_idx(r, kDist_classes);
    int brand = (int)(r.range(1, 10));
    int64_t brand_id = (cat + 1) * 1000000 + (cls + 1) * 1000 + brand;
    w.fint(brand_id);
    {
      char bn[40];
      snprintf(bn, sizeof bn, "%s #%d", kDist_classes.e[cls].v, brand);
      w.fstr(bn);  // i_brand
    }
    w.fint(cls + 1);
    w.fstr(kDist_classes.e[cls].v);
    w.fint(cat + 1);
    w.fstr(kDist_categories.e[cat].v);
    int64_t manu = r.range(1, 1000);
    w.fint(manu);
    {
      char mn[24];
      snprintf(mn, sizeof mn, "manu#%" PRId64, manu);
      w.fstr(mn);
    }
    w.fstr(pick(r, kSizes));
    w.fstr(sentence(r, 2));  // formulation
    w.fstr(dpick(r, kDist_colors));
    w.fstr(pick(r, kUnits));
    w.fstr(kContainers[0]);
    w.fint(r.range(1, 100));
    {
      char pn[32];
      snprintf(pn, sizeof pn, "%s%" PRId64, pick(r, kColors), sk);
      w.fstr(pn);  // i_product_name
    }
    w.endrow();
  }
}

static void gen_store(Writer& w, int64_t b, int64_t e) {
  for (int64_t i = b; i < e; i++) {
    int64_t sk = i + 1;
    Rng r(g_seed, T_STORE, i);
    w.fint(sk);
    w.fstr(bkey(sk));
    w.fdate(SALES_FIRST_JD - (int64_t)r.range(100, 2000));
    w.fnull();  // rec_end_date
    if (r.chance(0.1))
      w.fint(r.range(SALES_FIRST_JD, SALES_LAST_JD));
    else
      w.fnull();  // closed_date_sk
    w.fstr(std::string(pick(r, kLastNames)) + " Store");
    w.fint(r.range(200, 300));
    w.fint(r.range(5000000, 9999999));
    w.fstr(kHours[i % 3]);
    w.fstr(std::string(pick(r, kFirstNames)) + " " + pick(r, kLastNames));
    w.fint(r.range(1, 10));
    w.fstr(sentence(r, 6));
    w.fstr(sentence(r, 10));
    w.fstr(std::string(pick(r, kFirstNames)) + " " + pick(r, kLastNames));
    w.fint(r.range(1, 2));
    w.fstr("Division " + std::to_string(r.range(1, 2)));
    w.fint(r.range(1, 2));
    w.fstr("Company " + std::to_string(r.range(1, 2)));
    char num[16];
    snprintf(num, sizeof num, "%" PRId64, r.range(1, 999));
    w.fstr(num);
    w.fstr(pick(r, kStreetNames));
    w.fstr(pick(r, kStreetTypes));
    char suite[16];
    snprintf(suite, sizeof suite, "Suite %" PRId64, r.range(0, 99));
    w.fstr(suite);
    // stores draw from the small CONDITIONED pools (store_cities /
    // store_states / store_gmt): with only 12 stores at SF1, template
    // parameters predicating on s_city/s_state must share the exact
    // domain stores are assigned from or they match zero rows
    w.fstr(dpick(r, kDist_store_cities));
    w.fstr(dpick(r, kDist_fips_county));
    w.fstr(dpick(r, kDist_store_states));
    char zip[8];
    snprintf(zip, sizeof zip, "%05" PRId64, r.range(601, 99950));
    w.fstr(zip);
    w.fstr(kCountries[0]);
    w.fmoney(100 * dpick_int(r, kDist_store_gmt));
    w.fmoney(r.range(0, 11));  // tax percentage 0.00-0.11
    w.endrow();
  }
}

static void gen_call_center(Writer& w, int64_t b, int64_t e) {
  static const char* kCCNames[] = {"NY Metro", "Mid Atlantic", "Pacific NW",
      "North Midwest", "California", "New England", "Southeast", "Southwest",
      "Hawaii/Alaska", "Central", "Mountain", "Plains"};
  static const char* kCCClass[] = {"small", "medium", "large"};
  for (int64_t i = b; i < e; i++) {
    int64_t sk = i + 1;
    Rng r(g_seed, T_CALL_CENTER, i);
    w.fint(sk);
    w.fstr(bkey(sk));
    w.fdate(SALES_FIRST_JD - (int64_t)r.range(100, 2000));
    w.fnull();
    w.fnull();  // closed_date_sk
    w.fint(SALES_FIRST_JD - (int64_t)r.range(100, 2000));  // open_date_sk
    w.fstr(kCCNames[i % 12]);
    w.fstr(kCCClass[i % 3]);
    w.fint(r.range(100, 700));
    w.fint(r.range(10000, 40000));
    w.fstr(kHours[i % 3]);
    w.fstr(std::string(pick(r, kFirstNames)) + " " + pick(r, kLastNames));
    w.fint(r.range(1, 6));
    w.fstr(sentence(r, 3));
    w.fstr(sentence(r, 8));
    w.fstr(std::string(pick(r, kFirstNames)) + " " + pick(r, kLastNames));
    w.fint(r.range(1, 2));
    w.fstr("Division " + std::to_string(r.range(1, 2)));
    w.fint(r.range(1, 6));
    w.fstr("Company " + std::to_string(r.range(1, 6)));
    char num[16];
    snprintf(num, sizeof num, "%" PRId64, r.range(1, 999));
    w.fstr(num);
    w.fstr(pick(r, kStreetNames));
    w.fstr(pick(r, kStreetTypes));
    char suite[16];
    snprintf(suite, sizeof suite, "Suite %" PRId64, r.range(0, 99));
    w.fstr(suite);
    w.fstr(dpick(r, kDist_cities));
    w.fstr(dpick(r, kDist_fips_county));
    w.fstr(dpick(r, kDist_states));
    char zip[8];
    snprintf(zip, sizeof zip, "%05" PRId64, r.range(601, 99950));
    w.fstr(zip);
    w.fstr(kCountries[0]);
    w.fmoney(-100 * r.range(5, 10));
    w.fmoney(r.range(0, 11));
    w.endrow();
  }
}

static void gen_customer(Writer& w, int64_t b, int64_t e) {
  static const char* kBirthCountries[] = {"UNITED STATES", "CANADA", "MEXICO",
      "GERMANY", "FRANCE", "JAPAN", "CHINA", "INDIA", "BRAZIL", "ITALY",
      "NETHERLANDS", "PORTUGAL", "IRELAND", "GREECE", "TURKEY", "NIGERIA",
      "KENYA", "EGYPT", "PERU", "CHILE"};
  for (int64_t i = b; i < e; i++) {
    int64_t sk = i + 1;
    Rng r(g_seed, T_CUSTOMER, i);
    w.fint(sk);
    w.fstr(bkey(sk));
    if (r.chance(0.96)) w.fint(r.range(1, g_sz.customer_demographics)); else w.fnull();
    if (r.chance(0.96)) w.fint(r.range(1, g_sz.household_demographics)); else w.fnull();
    if (r.chance(0.96)) w.fint(r.range(1, g_sz.customer_address)); else w.fnull();
    int64_t first_sale = r.range(SALES_FIRST_JD - 1000, SALES_LAST_JD);
    w.fint(first_sale + r.range(0, 30));  // first shipto
    w.fint(first_sale);                   // first sales
    w.fstr(pick(r, kSalutations));
    const char* fn = pick(r, kFirstNames);
    w.fstr(fn);
    const char* ln = pick(r, kLastNames);
    w.fstr(ln);
    w.fstr(r.chance(0.5) ? "Y" : "N");
    w.fint(r.range(1, 28));
    w.fint(r.range(1, 12));
    w.fint(r.range(1924, 1992));
    w.fstr(kBirthCountries[r.next() % 20]);
    w.fnull();  // c_login
    {
      char email[80];
      snprintf(email, sizeof email, "%s.%s@example.com", fn, ln);
      w.fstr(email);
    }
    w.fint(r.range(SALES_LAST_JD - 400, SALES_LAST_JD));
    w.endrow();
  }
}

static void gen_web_site(Writer& w, int64_t b, int64_t e) {
  static const char* kSiteNames[] = {"site_0", "site_1", "site_2", "site_3",
      "site_4", "site_5"};
  for (int64_t i = b; i < e; i++) {
    int64_t sk = i + 1;
    Rng r(g_seed, T_WEB_SITE, i);
    w.fint(sk);
    w.fstr(bkey(sk));
    w.fdate(SALES_FIRST_JD - (int64_t)r.range(100, 2000));
    w.fnull();
    w.fstr(kSiteNames[i % 6]);
    w.fint(SALES_FIRST_JD - (int64_t)r.range(100, 2000));
    w.fnull();  // close date
    w.fstr(sentence(r, 2));
    w.fstr(std::string(pick(r, kFirstNames)) + " " + pick(r, kLastNames));
    w.fint(r.range(1, 6));
    w.fstr(sentence(r, 3));
    w.fstr(sentence(r, 8));
    w.fstr(std::string(pick(r, kFirstNames)) + " " + pick(r, kLastNames));
    w.fint(r.range(1, 2));
    w.fstr("Company " + std::to_string(r.range(1, 6)));
    char num[16];
    snprintf(num, sizeof num, "%" PRId64, r.range(1, 999));
    w.fstr(num);
    w.fstr(pick(r, kStreetNames));
    w.fstr(pick(r, kStreetTypes));
    char suite[16];
    snprintf(suite, sizeof suite, "Suite %" PRId64, r.range(0, 99));
    w.fstr(suite);
    w.fstr(dpick(r, kDist_cities));
    w.fstr(dpick(r, kDist_fips_county));
    w.fstr(dpick(r, kDist_states));
    char zip[8];
    snprintf(zip, sizeof zip, "%05" PRId64, r.range(601, 99950));
    w.fstr(zip);
    w.fstr(kCountries[0]);
    w.fmoney(-100 * r.range(5, 10));
    w.fmoney(r.range(0, 11));
    w.endrow();
  }
}

static void gen_household_demographics(Writer& w, int64_t b, int64_t e) {
  for (int64_t i = b; i < e; i++) {
    int64_t sk = i + 1, v = i;
    int ib = v % 20; v /= 20;
    int bp = v % kDist_buy_potential.n; v /= kDist_buy_potential.n;
    int dep = v % 10; v /= 10;
    int veh = v % 6;
    w.fint(sk);
    w.fint(ib + 1);
    w.fstr(kDist_buy_potential.e[bp].v);
    w.fint(dep);
    w.fint(veh - 1 + 1);
    w.endrow();
  }
}

static void gen_web_page(Writer& w, int64_t b, int64_t e) {
  static const char* kPageTypes[] = {"ad", "dynamic", "feedback", "general",
      "order", "protected", "welcome"};
  for (int64_t i = b; i < e; i++) {
    int64_t sk = i + 1;
    Rng r(g_seed, T_WEB_PAGE, i);
    w.fint(sk);
    w.fstr(bkey(sk));
    w.fdate(SALES_FIRST_JD - (int64_t)r.range(100, 2000));
    w.fnull();
    w.fint(SALES_FIRST_JD - (int64_t)r.range(0, 1000));
    w.fint(SALES_FIRST_JD + (int64_t)r.range(0, 1000));
    w.fstr(r.chance(0.3) ? "Y" : "N");
    if (r.chance(0.2)) w.fint(r.range(1, g_sz.customer)); else w.fnull();
    w.fstr("http://www.example.com/page_" + std::to_string(sk));
    w.fstr(kPageTypes[i % 7]);
    w.fint(r.range(100, 7000));
    w.fint(r.range(2, 25));
    w.fint(r.range(1, 7));
    w.fint(r.range(0, 4));
    w.endrow();
  }
}

static void gen_promotion(Writer& w, int64_t b, int64_t e) {
  static const char* kPurpose[] = {"Unknown", "ad", "discount", "coupon"};
  for (int64_t i = b; i < e; i++) {
    int64_t sk = i + 1;
    Rng r(g_seed, T_PROMOTION, i);
    w.fint(sk);
    w.fstr(bkey(sk));
    int64_t start = r.range(SALES_FIRST_JD, SALES_LAST_JD - 60);
    w.fint(start);
    w.fint(start + r.range(10, 60));
    w.fint(r.range(1, g_sz.item));
    w.fmoney(100000);  // p_cost 1000.00
    w.fint(r.range(1, 3));
    {
      char pn[24];
      snprintf(pn, sizeof pn, "promo_%" PRId64, sk);
      w.fstr(pn);
    }
    for (int c = 0; c < 8; c++) w.fstr(r.chance(0.5) ? "Y" : "N");
    w.fstr(sentence(r, 5));
    w.fstr(kPurpose[i % 4]);
    w.fstr(r.chance(0.5) ? "Y" : "N");
    w.endrow();
  }
}

static void gen_catalog_page(Writer& w, int64_t b, int64_t e) {
  static const char* kCpTypes[] = {"bi-annual", "quarterly", "monthly"};
  for (int64_t i = b; i < e; i++) {
    int64_t sk = i + 1;
    Rng r(g_seed, T_CATALOG_PAGE, i);
    w.fint(sk);
    w.fstr(bkey(sk));
    int64_t start = SALES_FIRST_JD + (i / 108) * 30;
    w.fint(start);
    w.fint(start + 90);
    w.fstr("DEPARTMENT");
    w.fint(i / 108 + 1);
    w.fint(i % 108 + 1);
    w.fstr(sentence(r, 8));
    w.fstr(kCpTypes[i % 3]);
    w.endrow();
  }
}

static void gen_inventory(Writer& w, int64_t b, int64_t e) {
  int64_t items = g_sz.item / 2 < 1 ? 1 : g_sz.item / 2;
  int64_t wh = g_sz.warehouse;
  for (int64_t i = b; i < e; i++) {
    Rng r(g_seed, T_INVENTORY, i);
    int64_t week = i / (items * wh);
    int64_t rem = i % (items * wh);
    int64_t item = (rem / wh) * 2 + 1;  // every other item is stocked
    int64_t warehouse = rem % wh + 1;
    w.fint(SALES_FIRST_JD - 7 + week * 7);  // weekly date_sk
    w.fint(item);
    w.fint(warehouse);
    if (r.chance(0.05))
      w.fnull();
    else
      w.fint(r.range(0, 1000));
    w.endrow();
  }
}

static void gen_dbgen_version(Writer& w, int64_t b, int64_t e) {
  (void)b; (void)e;
  w.fstr("ndsgen-1.0");
  w.fdate(SALES_LAST_JD);
  w.fstr("00:00:00");
  w.fstr("-scale");
  w.endrow();
}

// ---------------------------------------------------------------------------
// Fact generators
// ---------------------------------------------------------------------------

static void gen_store_sales(Writer& w, int64_t b, int64_t e) {
  for (int64_t i = b; i < e; i++) {
    SaleCore s = gen_sale(T_STORE_SALES, i, g_sz.store, TICKET_SPREAD);
    if (s.null_date) w.fnull(); else w.fint(s.date_sk);
    w.fint(s.time_sk);
    w.fint(s.item_sk);
    if (s.null_customer) w.fnull(); else w.fint(s.customer_sk);
    w.fint(s.cdemo_sk);
    w.fint(s.hdemo_sk);
    w.fint(s.addr_sk);
    if (s.null_channel) w.fnull(); else w.fint(s.channel_sk);
    if (s.null_promo) w.fnull(); else w.fint(s.promo_sk);
    w.fint(s.ticket);
    w.fint(s.quantity);
    w.fmoney(s.wholesale);
    w.fmoney(s.list);
    w.fmoney(s.sales);
    w.fmoney(s.ext_discount);
    w.fmoney(s.ext_sales);
    w.fmoney(s.ext_wholesale);
    w.fmoney(s.ext_list);
    w.fmoney(s.ext_tax);
    w.fmoney(s.coupon);
    w.fmoney(s.net_paid);
    w.fmoney(s.net_paid_tax);
    w.fmoney(s.net_profit);
    w.endrow();
  }
}

static void gen_catalog_sales(Writer& w, int64_t b, int64_t e) {
  for (int64_t i = b; i < e; i++) {
    SaleCore s = gen_sale(T_CATALOG_SALES, i, g_sz.call_center, TICKET_SPREAD);
    Rng r2(g_seed, T_CATALOG_SALES + 100, i);  // extra columns stream
    int64_t ship_date = s.date_sk + r2.range(2, 120);
    if (s.null_date) w.fnull(); else w.fint(s.date_sk);
    w.fint(s.time_sk);
    w.fint(ship_date);
    if (s.null_customer) w.fnull(); else w.fint(s.customer_sk);
    w.fint(s.cdemo_sk);
    w.fint(s.hdemo_sk);
    w.fint(s.addr_sk);
    // ship-to: usually same customer
    int64_t ship_cust = r2.chance(0.85) ? s.customer_sk
                                        : r2.range(1, g_sz.customer);
    if (s.null_customer) w.fnull(); else w.fint(ship_cust);
    w.fint(r2.range(1, g_sz.customer_demographics));
    w.fint(r2.range(1, g_sz.household_demographics));
    w.fint(r2.range(1, g_sz.customer_address));
    if (s.null_channel) w.fnull(); else w.fint(s.channel_sk);
    w.fint(r2.range(1, g_sz.catalog_page));
    w.fint(r2.range(1, g_sz.ship_mode));
    w.fint(r2.range(1, g_sz.warehouse));
    w.fint(s.item_sk);
    if (s.null_promo) w.fnull(); else w.fint(s.promo_sk);
    w.fint(s.ticket);  // cs_order_number
    w.fint(s.quantity);
    w.fmoney(s.wholesale);
    w.fmoney(s.list);
    w.fmoney(s.sales);
    w.fmoney(s.ext_discount);
    w.fmoney(s.ext_sales);
    w.fmoney(s.ext_wholesale);
    w.fmoney(s.ext_list);
    w.fmoney(s.ext_tax);
    w.fmoney(s.coupon);
    int64_t ship_cost = (s.ext_list * r2.range(0, 50)) / 1000;
    w.fmoney(ship_cost);
    w.fmoney(s.net_paid);
    w.fmoney(s.net_paid_tax);
    w.fmoney(s.net_paid + ship_cost);
    w.fmoney(s.net_paid_tax + ship_cost);
    w.fmoney(s.net_profit);
    w.endrow();
  }
}

static void gen_web_sales(Writer& w, int64_t b, int64_t e) {
  for (int64_t i = b; i < e; i++) {
    SaleCore s = gen_sale(T_WEB_SALES, i, g_sz.web_site, TICKET_SPREAD);
    Rng r2(g_seed, T_WEB_SALES + 100, i);
    int64_t ship_date = s.date_sk + r2.range(2, 120);
    if (s.null_date) w.fnull(); else w.fint(s.date_sk);
    w.fint(s.time_sk);
    w.fint(ship_date);
    w.fint(s.item_sk);
    if (s.null_customer) w.fnull(); else w.fint(s.customer_sk);
    w.fint(s.cdemo_sk);
    w.fint(s.hdemo_sk);
    w.fint(s.addr_sk);
    int64_t ship_cust = r2.chance(0.85) ? s.customer_sk
                                        : r2.range(1, g_sz.customer);
    if (s.null_customer) w.fnull(); else w.fint(ship_cust);
    w.fint(r2.range(1, g_sz.customer_demographics));
    w.fint(r2.range(1, g_sz.household_demographics));
    w.fint(r2.range(1, g_sz.customer_address));
    w.fint(r2.range(1, g_sz.web_page));
    if (s.null_channel) w.fnull(); else w.fint(s.channel_sk);
    w.fint(r2.range(1, g_sz.ship_mode));
    w.fint(r2.range(1, g_sz.warehouse));
    if (s.null_promo) w.fnull(); else w.fint(s.promo_sk);
    w.fint(s.ticket);  // ws_order_number
    w.fint(s.quantity);
    w.fmoney(s.wholesale);
    w.fmoney(s.list);
    w.fmoney(s.sales);
    w.fmoney(s.ext_discount);
    w.fmoney(s.ext_sales);
    w.fmoney(s.ext_wholesale);
    w.fmoney(s.ext_list);
    w.fmoney(s.ext_tax);
    w.fmoney(s.coupon);
    int64_t ship_cost = (s.ext_list * r2.range(0, 50)) / 1000;
    w.fmoney(ship_cost);
    w.fmoney(s.net_paid);
    w.fmoney(s.net_paid_tax);
    w.fmoney(s.net_paid + ship_cost);
    w.fmoney(s.net_paid_tax + ship_cost);
    w.fmoney(s.net_profit);
    w.endrow();
  }
}

static void gen_store_returns(Writer& w, int64_t b, int64_t e) {
  for (int64_t j = b; j < e; j++) {
    int64_t i = return_parent_row(j, g_sz.store_sales, g_sz.store_returns);
    SaleCore s = gen_sale(T_STORE_SALES, i, g_sz.store, TICKET_SPREAD);
    RetCore t = gen_return(T_STORE_RETURNS, j, s);
    w.fint(t.ret_date_sk);
    w.fint(t.ret_time_sk);
    w.fint(s.item_sk);
    if (s.null_customer) w.fnull(); else w.fint(s.customer_sk);
    w.fint(s.cdemo_sk);
    w.fint(s.hdemo_sk);
    w.fint(s.addr_sk);
    if (s.null_channel) w.fnull(); else w.fint(s.channel_sk);
    w.fint(t.reason_sk);
    w.fint(s.ticket);
    w.fint(t.qty);
    w.fmoney(t.amt);
    w.fmoney(t.tax);
    w.fmoney(t.amt_inc_tax);
    w.fmoney(t.fee);
    w.fmoney(t.ship_cost);
    w.fmoney(t.refunded);
    w.fmoney(t.reversed);
    w.fmoney(t.credit);
    w.fmoney(t.net_loss);
    w.endrow();
  }
}

static void gen_catalog_returns(Writer& w, int64_t b, int64_t e) {
  for (int64_t j = b; j < e; j++) {
    int64_t i = return_parent_row(j, g_sz.catalog_sales, g_sz.catalog_returns);
    SaleCore s = gen_sale(T_CATALOG_SALES, i, g_sz.call_center, TICKET_SPREAD);
    Rng r2(g_seed, T_CATALOG_SALES + 100, i);
    RetCore t = gen_return(T_CATALOG_RETURNS, j, s);
    w.fint(t.ret_date_sk);
    w.fint(t.ret_time_sk);
    w.fint(s.item_sk);
    if (s.null_customer) w.fnull(); else w.fint(s.customer_sk);
    w.fint(s.cdemo_sk);
    w.fint(s.hdemo_sk);
    w.fint(s.addr_sk);
    if (s.null_customer) w.fnull(); else w.fint(s.customer_sk);  // returning =
    w.fint(s.cdemo_sk);
    w.fint(s.hdemo_sk);
    w.fint(s.addr_sk);
    if (s.null_channel) w.fnull(); else w.fint(s.channel_sk);
    w.fint(r2.range(1, g_sz.catalog_page));
    w.fint(r2.range(1, g_sz.ship_mode));
    w.fint(r2.range(1, g_sz.warehouse));
    w.fint(t.reason_sk);
    w.fint(s.ticket);
    w.fint(t.qty);
    w.fmoney(t.amt);
    w.fmoney(t.tax);
    w.fmoney(t.amt_inc_tax);
    w.fmoney(t.fee);
    w.fmoney(t.ship_cost);
    w.fmoney(t.refunded);
    w.fmoney(t.reversed);
    w.fmoney(t.credit);
    w.fmoney(t.net_loss);
    w.endrow();
  }
}

static void gen_web_returns(Writer& w, int64_t b, int64_t e) {
  for (int64_t j = b; j < e; j++) {
    int64_t i = return_parent_row(j, g_sz.web_sales, g_sz.web_returns);
    SaleCore s = gen_sale(T_WEB_SALES, i, g_sz.web_site, TICKET_SPREAD);
    Rng r2(g_seed, T_WEB_SALES + 100, i);
    RetCore t = gen_return(T_WEB_RETURNS, j, s);
    w.fint(t.ret_date_sk);
    w.fint(t.ret_time_sk);
    w.fint(s.item_sk);
    if (s.null_customer) w.fnull(); else w.fint(s.customer_sk);
    w.fint(s.cdemo_sk);
    w.fint(s.hdemo_sk);
    w.fint(s.addr_sk);
    if (s.null_customer) w.fnull(); else w.fint(s.customer_sk);
    w.fint(s.cdemo_sk);
    w.fint(s.hdemo_sk);
    w.fint(s.addr_sk);
    w.fint(r2.range(1, g_sz.web_page));
    w.fint(t.reason_sk);
    w.fint(s.ticket);
    w.fint(t.qty);
    w.fmoney(t.amt);
    w.fmoney(t.tax);
    w.fmoney(t.amt_inc_tax);
    w.fmoney(t.fee);
    w.fmoney(t.ship_cost);
    w.fmoney(t.refunded);
    w.fmoney(t.reversed);
    w.fmoney(t.credit);
    w.fmoney(t.net_loss);
    w.endrow();
  }
}

// ---------------------------------------------------------------------------
// Refresh ("update") set generators — staging tables for data maintenance
// plus the delete/inventory_delete date-range tables
// (reference: nds_gen_data.py:70-83,119-127; data_maintenance/*.sql).
// ---------------------------------------------------------------------------

static void fdate10(Writer& w, int64_t jd) {  // char(10) date for staging
  Civil c = civil_from_days(jd - JD_EPOCH_1970);
  char b[16];
  snprintf(b, sizeof b, "%04d-%02d-%02d", c.y, c.m, c.d);
  w.fstr(b);
}

// the k-th update set covers a 1-month slice after the sales window
static void update_window(int update, int64_t* lo, int64_t* hi) {
  *lo = SALES_LAST_JD + 1 + (int64_t)(update - 1) * 30;
  *hi = *lo + 29;
}

static void gen_s_purchase(Writer& w, int update, int64_t b, int64_t e) {
  int64_t lo, hi;
  update_window(update, &lo, &hi);
  for (int64_t i = b; i < e; i++) {
    Rng r(g_seed + update, T_S_PURCHASE, i);
    w.fint(i + 1);
    w.fstr(bkey(r.range(1, g_sz.store)));
    w.fstr(bkey(r.range(1, g_sz.customer)));
    fdate10(w, r.range(lo, hi));
    w.fint(r.range(0, 86399));
    w.fint(r.range(1, 1000));
    w.fint(r.range(1, 1000));
    w.fstr(sentence(r, 6));
    w.endrow();
  }
}

static void gen_s_lineitems(Writer& w, uint64_t tid, int update, int64_t b,
                            int64_t e, int per_order, bool catalog, bool web) {
  for (int64_t o = b; o < e; o++) {
    for (int li = 1; li <= per_order; li++) {
      Rng r(g_seed + update, tid, o * 100 + li);
      w.fint(o + 1);
      w.fint(li);
      w.fstr(bkey(r.range(1, g_sz.item)));
      if (r.chance(0.5)) w.fstr(bkey(r.range(1, g_sz.promotion))); else w.fnull();
      w.fint(r.range(1, 100));
      w.fmoney(r.cents(100, 10000));
      w.fmoney(r.chance(0.15) ? r.cents(0, 5000) : 0);
      if (catalog || web) {
        int64_t lo, hi;
        update_window(update, &lo, &hi);
        w.fstr(bkey(r.range(1, g_sz.warehouse)));
        fdate10(w, r.range(lo, hi));
        if (catalog) {
          w.fint(r.range(1, 109));
          w.fint(r.range(1, 108));
        }
        w.fmoney(r.cents(0, 5000));
        if (web) w.fstr(bkey(r.range(1, g_sz.web_page)));
      } else {
        w.fstr(sentence(r, 4));  // plin_comment
      }
      w.endrow();
    }
  }
}

static void gen_s_order(Writer& w, uint64_t tid, int update, int64_t b,
                        int64_t e, bool web) {
  int64_t lo, hi;
  update_window(update, &lo, &hi);
  for (int64_t i = b; i < e; i++) {
    Rng r(g_seed + update, tid, i);
    w.fint(i + 1);
    w.fstr(bkey(r.range(1, g_sz.customer)));
    w.fstr(bkey(r.range(1, g_sz.customer)));
    fdate10(w, r.range(lo, hi));
    w.fint(r.range(0, 86399));
    w.fstr(bkey(r.range(1, g_sz.ship_mode)));
    w.fstr(bkey(web ? r.range(1, g_sz.web_site) : r.range(1, g_sz.call_center)));
    w.fstr(sentence(r, 6));
    w.endrow();
  }
}

static void gen_s_returns(Writer& w, uint64_t tid, int update, int64_t b,
                          int64_t e, int kind) {  // 0=store 1=catalog 2=web
  int64_t lo, hi;
  update_window(update, &lo, &hi);
  for (int64_t i = b; i < e; i++) {
    Rng r(g_seed + update, tid, i);
    int64_t amt = r.cents(100, 20000);
    int64_t tax = amt / 10;
    if (kind == 0) {
      w.fstr(bkey(r.range(1, g_sz.store)));
      w.fstr(bkey(i + 1));  // purchase id
      w.fint(r.range(1, 10));
      w.fstr(bkey(r.range(1, g_sz.item)));
      w.fstr(bkey(r.range(1, g_sz.customer)));
      fdate10(w, r.range(lo, hi));
      w.fstr("12:00:00");
      w.fint(r.range(1, g_sz.store_sales / TICKET_SPREAD + 1));
      w.fint(r.range(1, 50));
      w.fmoney(amt); w.fmoney(tax); w.fmoney(r.cents(50, 5000));
      w.fmoney(r.cents(0, 5000)); w.fmoney(amt / 2); w.fmoney(amt / 4);
      w.fmoney(amt / 4);
      w.fstr(bkey(r.range(1, g_sz.reason)));
    } else if (kind == 1) {
      w.fstr(bkey(r.range(1, g_sz.call_center)));
      w.fint(i + 1);
      w.fint(r.range(1, 10));
      w.fstr(bkey(r.range(1, g_sz.item)));
      w.fstr(bkey(r.range(1, g_sz.customer)));
      w.fstr(bkey(r.range(1, g_sz.customer)));
      fdate10(w, r.range(lo, hi));
      w.fstr("12:00:00");
      w.fint(r.range(1, 50));
      w.fmoney(amt); w.fmoney(tax); w.fmoney(r.cents(50, 5000));
      w.fmoney(r.cents(0, 5000)); w.fmoney(amt / 2); w.fmoney(amt / 4);
      w.fmoney(amt / 4);
      w.fstr(bkey(r.range(1, g_sz.reason)));
      w.fstr(bkey(r.range(1, g_sz.ship_mode)));
      w.fstr(bkey(r.range(1, g_sz.catalog_page)));
      w.fstr(bkey(r.range(1, g_sz.warehouse)));
    } else {
      w.fstr(bkey(r.range(1, g_sz.web_page)));
      w.fint(i + 1);
      w.fint(r.range(1, 10));
      w.fstr(bkey(r.range(1, g_sz.item)));
      w.fstr(bkey(r.range(1, g_sz.customer)));
      w.fstr(bkey(r.range(1, g_sz.customer)));
      fdate10(w, r.range(lo, hi));
      w.fstr("12:00:00");
      w.fint(r.range(1, 50));
      w.fmoney(amt); w.fmoney(tax); w.fmoney(r.cents(50, 5000));
      w.fmoney(r.cents(0, 5000)); w.fmoney(amt / 2); w.fmoney(amt / 4);
      w.fmoney(amt / 4);
      w.fstr(bkey(r.range(1, g_sz.reason)));
    }
    w.endrow();
  }
}

static void gen_s_inventory(Writer& w, int update, int64_t b, int64_t e) {
  int64_t lo, hi;
  update_window(update, &lo, &hi);
  for (int64_t i = b; i < e; i++) {
    Rng r(g_seed + update, T_S_INVENTORY, i);
    w.fstr(bkey(r.range(1, g_sz.warehouse)));
    w.fstr(bkey(r.range(1, g_sz.item)));
    fdate10(w, lo + (i % 4) * 7);
    w.fint(r.range(0, 1000));
    w.endrow();
  }
}

static void gen_delete_table(Writer& w, uint64_t tid, int update) {
  // 3 (date1, date2) ranges inside the historical sales window; DM delete
  // functions remove facts whose date_sk falls between them.
  for (int64_t i = 0; i < 3; i++) {
    Rng r(g_seed + update, tid, i);
    int64_t span = (SALES_LAST_JD - SALES_FIRST_JD) / 20;
    int64_t lo = SALES_FIRST_JD + (int64_t)(r.next() % (uint64_t)(SALES_LAST_JD -
                                                        SALES_FIRST_JD - span));
    fdate10(w, lo);
    fdate10(w, lo + span);
    w.endrow();
  }
}

// ---------------------------------------------------------------------------
// main
// ---------------------------------------------------------------------------

struct TableDef {
  const char* name;
  void (*gen)(Writer&, int64_t, int64_t);
  int64_t Sizes::*count;
};

int main(int argc, char** argv) {
  double sf = 1.0;
  std::string dir = ".";
  std::string only_table;
  int parallel = 1, child = 1, update = 0;
  for (int i = 1; i < argc; i++) {
    std::string a = argv[i];
    auto need = [&](const char* what) -> const char* {
      if (i + 1 >= argc) {
        fprintf(stderr, "ndsgen: %s needs a value\n", what);
        exit(2);
      }
      return argv[++i];
    };
    if (a == "-scale") sf = atof(need("-scale"));
    else if (a == "-sizes") {
      // print the scaling model for a scale factor (no generation) —
      // lets tests lock the spec step-table counts cheaply
      Sizes s = compute_sizes(atof(need("-sizes")));
      printf("store_sales|%lld\n", (long long)s.store_sales);
      printf("catalog_sales|%lld\n", (long long)s.catalog_sales);
      printf("web_sales|%lld\n", (long long)s.web_sales);
      printf("store_returns|%lld\n", (long long)s.store_returns);
      printf("catalog_returns|%lld\n", (long long)s.catalog_returns);
      printf("web_returns|%lld\n", (long long)s.web_returns);
      printf("inventory|%lld\n", (long long)s.inventory);
      printf("item|%lld\n", (long long)s.item);
      printf("customer|%lld\n", (long long)s.customer);
      printf("customer_address|%lld\n", (long long)s.customer_address);
      printf("customer_demographics|%lld\n",
             (long long)s.customer_demographics);
      printf("household_demographics|%lld\n",
             (long long)s.household_demographics);
      printf("income_band|%lld\n", (long long)s.income_band);
      printf("store|%lld\n", (long long)s.store);
      printf("warehouse|%lld\n", (long long)s.warehouse);
      printf("web_site|%lld\n", (long long)s.web_site);
      printf("web_page|%lld\n", (long long)s.web_page);
      printf("promotion|%lld\n", (long long)s.promotion);
      printf("catalog_page|%lld\n", (long long)s.catalog_page);
      printf("call_center|%lld\n", (long long)s.call_center);
      printf("ship_mode|%lld\n", (long long)s.ship_mode);
      printf("reason|%lld\n", (long long)s.reason);
      printf("time_dim|%lld\n", (long long)s.time_dim);
      printf("date_dim|%lld\n", (long long)s.date_dim);
      return 0;
    }
    else if (a == "-dir") dir = need("-dir");
    else if (a == "-table") only_table = need("-table");
    else if (a == "-parallel") parallel = atoi(need("-parallel"));
    else if (a == "-child") child = atoi(need("-child"));
    else if (a == "-update") update = atoi(need("-update"));
    else if (a == "-seed") g_seed = (uint64_t)atoll(need("-seed"));
    else if (a == "-h" || a == "--help") {
      printf("usage: ndsgen -scale SF -dir DIR [-parallel N -child I] "
             "[-table T] [-update K] [-seed S] | -sizes SF\n"
             "  -sizes SF  print the row-count scaling model (spec step "
             "table) and exit\n");
      return 0;
    } else {
      fprintf(stderr, "ndsgen: unknown arg %s\n", a.c_str());
      return 2;
    }
  }
  if (parallel < 1 || child < 1 || child > parallel) {
    fprintf(stderr, "ndsgen: bad -parallel/-child\n");
    return 2;
  }
  g_sz = compute_sizes(sf);

  char suffix[64];
  snprintf(suffix, sizeof suffix, "_%d_%d.dat", child, parallel);

  if (update > 0) {
    // refresh set sizing: proportional to SF, small.  Each job's natural
    // unit count (rows or orders) is chunked across -parallel children so
    // the driver's fan-out never duplicates content.
    int64_t orders = lin(sf, 1500);
    auto at_least_1 = [](int64_t n) { return n < 1 ? 1 : n; };
    struct {
      const char* name;
      int which;
      int64_t n;
    } jobs[] = {{"s_purchase", 0, orders},
                {"s_purchase_lineitem", 1, orders},
                {"s_catalog_order", 2, at_least_1(orders / 2)},
                {"s_catalog_order_lineitem", 3, at_least_1(orders / 2)},
                {"s_web_order", 4, at_least_1(orders / 3)},
                {"s_web_order_lineitem", 5, at_least_1(orders / 3)},
                {"s_store_returns", 6, at_least_1(orders / 5)},
                {"s_catalog_returns", 7, at_least_1(orders / 8)},
                {"s_web_returns", 8, at_least_1(orders / 10)},
                {"s_inventory", 9, at_least_1(orders / 2)},
                {"delete", 10, 1},
                {"inventory_delete", 11, 1}};
    for (auto& j : jobs) {
      if (!only_table.empty() && only_table != j.name) continue;
      if (j.which >= 10) {
        // delete-date tables: tiny, identical content — child 1 only
        // (cf. reference note in nds_gen_data.py:119-123)
        if (child != 1 && only_table.empty()) continue;
        Writer w(dir + "/" + j.name + suffix);
        gen_delete_table(w, j.which == 10 ? T_DELETE : T_INVENTORY_DELETE,
                         update);
        continue;
      }
      int64_t b, e;
      chunk(j.n, parallel, child, &b, &e);
      if (b >= e && parallel > 1) continue;
      Writer w(dir + "/" + j.name + suffix);
      switch (j.which) {
        case 0: gen_s_purchase(w, update, b, e); break;
        case 1: gen_s_lineitems(w, T_S_PURCHASE_LINEITEM, update, b, e, 3,
                                false, false); break;
        case 2: gen_s_order(w, T_S_CATALOG_ORDER, update, b, e, false); break;
        case 3: gen_s_lineitems(w, T_S_CATALOG_ORDER_LINEITEM, update, b, e,
                                3, true, false); break;
        case 4: gen_s_order(w, T_S_WEB_ORDER, update, b, e, true); break;
        case 5: gen_s_lineitems(w, T_S_WEB_ORDER_LINEITEM, update, b, e, 3,
                                false, true); break;
        case 6: gen_s_returns(w, T_S_STORE_RETURNS, update, b, e, 0); break;
        case 7: gen_s_returns(w, T_S_CATALOG_RETURNS, update, b, e, 1); break;
        case 8: gen_s_returns(w, T_S_WEB_RETURNS, update, b, e, 2); break;
        case 9: gen_s_inventory(w, update, b, e); break;
      }
    }
    return 0;
  }

  static const TableDef tables[] = {
      {"customer_address", gen_customer_address, &Sizes::customer_address},
      {"customer_demographics", gen_customer_demographics,
       &Sizes::customer_demographics},
      {"date_dim", gen_date_dim, &Sizes::date_dim},
      {"warehouse", gen_warehouse, &Sizes::warehouse},
      {"ship_mode", gen_ship_mode, &Sizes::ship_mode},
      {"time_dim", gen_time_dim, &Sizes::time_dim},
      {"reason", gen_reason, &Sizes::reason},
      {"income_band", gen_income_band, &Sizes::income_band},
      {"item", gen_item, &Sizes::item},
      {"store", gen_store, &Sizes::store},
      {"call_center", gen_call_center, &Sizes::call_center},
      {"customer", gen_customer, &Sizes::customer},
      {"web_site", gen_web_site, &Sizes::web_site},
      {"store_returns", gen_store_returns, &Sizes::store_returns},
      {"household_demographics", gen_household_demographics,
       &Sizes::household_demographics},
      {"web_page", gen_web_page, &Sizes::web_page},
      {"promotion", gen_promotion, &Sizes::promotion},
      {"catalog_page", gen_catalog_page, &Sizes::catalog_page},
      {"inventory", gen_inventory, &Sizes::inventory},
      {"catalog_returns", gen_catalog_returns, &Sizes::catalog_returns},
      {"web_returns", gen_web_returns, &Sizes::web_returns},
      {"web_sales", gen_web_sales, &Sizes::web_sales},
      {"catalog_sales", gen_catalog_sales, &Sizes::catalog_sales},
      {"store_sales", gen_store_sales, &Sizes::store_sales},
  };

  for (auto& t : tables) {
    if (!only_table.empty() && only_table != t.name) continue;
    int64_t n = g_sz.*(t.count);
    int64_t b, e;
    chunk(n, parallel, child, &b, &e);
    if (b >= e && parallel > 1) continue;  // empty chunk: no file (dsdgen-like)
    Writer w(dir + "/" + std::string(t.name) + suffix);
    t.gen(w, b, e);
  }
  // dbgen_version: single row, child 1 only
  if ((only_table.empty() && child == 1) || only_table == "dbgen_version") {
    Writer w(dir + "/dbgen_version" + suffix);
    gen_dbgen_version(w, 0, 1);
  }
  return 0;
}
