"""Span tracer + metrics registry (dependency-free, stdlib only).

One process-global :class:`Tracer` records a tree of timed spans
(``phase > stream > query > plan-node``) plus named counters and gauges.
Tracing defaults ON and is disabled with ``NDSTPU_TRACE=0``; a disabled
tracer hands out a shared no-op span, so instrumented code pays one
attribute read and nothing else.  Tracing never touches query data —
it only reads clocks and appends to in-process lists.

Cost-attribution model ("buckets"):

* A span may carry a *bucket* (``compile_s`` / ``execute_s``) naming the
  cost category its wall time belongs to.
* A span may be a *collector* (``collect=True``; the per-query spans the
  harness opens are).  When a bucketed span finishes, its SELF time —
  wall minus the wall of bucketed spans nested inside it — is added to
  the nearest enclosing collector's bucket totals.  Self-time accounting
  means nested buckets never double count: a ``compile_s`` discovery
  span inside an ``execute_s`` statement span splits the statement wall
  into compile + the remainder, and the bucket totals of a collector
  sum to (at most) its own wall.
* Collectors roll their bucket totals up into the nearest enclosing
  collector when they finish, so a stream span collects what its query
  spans collected.

Threading: each thread has its own span stack (the harness runs queries
under a watchdog thread).  A span opened on a thread with an empty
stack attributes to the most recently entered collector process-wide,
so worker-thread engine spans still land in the open query span.

Clocks: durations are ``time.perf_counter`` deltas (monotonic); every
span also records an epoch-anchored start timestamp so traces from
concurrent processes (throughput streams) can be laid side by side.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional


def env_enabled() -> bool:
    """NDSTPU_TRACE=0 (or empty/false) disables tracing; default on."""
    return os.environ.get("NDSTPU_TRACE", "1").lower() not in (
        "", "0", "false", "off")


class _NullSpan:
    """Shared no-op span: the disabled-tracer fast path."""

    __slots__ = ()
    wall_s = 0.0
    buckets: Dict[str, float] = {}
    attrs: Dict[str, object] = {}

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):
        return self


NULL_SPAN = _NullSpan()


class Span:
    """One timed region.  Context manager; not reusable."""

    __slots__ = ("tracer", "name", "cat", "bucket", "collect", "attrs",
                 "parent", "collector", "parent_collector", "buckets",
                 "child_bucketed_s", "t0", "t0_epoch", "wall_s", "tid",
                 "depth", "seq")

    def __init__(self, tracer: "Tracer", name: str, cat: str,
                 bucket: Optional[str], collect: bool, attrs: dict):
        self.tracer = tracer
        self.name = name
        self.cat = cat
        self.bucket = bucket
        self.collect = collect
        self.attrs = attrs
        self.buckets: Dict[str, float] = {} if collect else None
        self.child_bucketed_s = 0.0
        self.wall_s = 0.0

    def __enter__(self):
        t = self.tracer
        stack = t._stack()
        self.parent = stack[-1] if stack else None
        self.depth = len(stack)
        if self.parent is not None:
            enclosing = self.parent.collector
        else:
            # cross-thread fallback: a span opened at the top of a worker
            # thread still attributes to the process's open query span
            enclosing = t._fallback_collector
        self.parent_collector = enclosing
        self.collector = self if self.collect else enclosing
        if self.collect:
            t._fallback_collector = self
        stack.append(self)
        self.tid = threading.get_ident()
        self.seq = t._next_seq()
        self.t0_epoch = time.time()
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        t1 = time.perf_counter()
        t = self.tracer
        stack = t._stack()
        while stack and stack.pop() is not self:
            pass  # robustness: a leaked child must not wedge the stack
        self.wall_s = t1 - self.t0
        if exc_type is not None:
            self.attrs["error"] = exc_type.__name__
        if self.collect and t._fallback_collector is self:
            t._fallback_collector = self.parent_collector
        if self.bucket:
            self_s = max(self.wall_s - self.child_bucketed_s, 0.0)
            coll = self.collector
            if coll is not None and coll.buckets is not None:
                coll.buckets[self.bucket] = (
                    coll.buckets.get(self.bucket, 0.0) + self_s)
            if self.parent is not None:
                # the FULL wall (self + nested buckets) is already
                # accounted below this span; the parent must subtract
                # all of it from its own self time
                self.parent.child_bucketed_s += self.wall_s
        elif self.parent is not None:
            # transparent span: bucketed grandchildren still subtract
            # from an outer bucketed ancestor
            self.parent.child_bucketed_s += self.child_bucketed_s
        if self.collect and self.buckets:
            up = self.parent_collector
            if up is not None and up.buckets is not None:
                for k, v in self.buckets.items():
                    up.buckets[k] = up.buckets.get(k, 0.0) + v
        t._finish(self)
        return False

    def set(self, **attrs) -> "Span":
        self.attrs.update(attrs)
        return self


class Tracer:
    """Process-global span recorder + counter/gauge registry."""

    def __init__(self, enabled: Optional[bool] = None):
        self.enabled = env_enabled() if enabled is None else enabled
        self._lock = threading.Lock()
        self._local = threading.local()
        self._fallback_collector: Optional[Span] = None
        self._seq = 0
        self.events: List[dict] = []      # finished spans, end order
        self.counters: Dict[str, float] = {}
        self.gauges: Dict[str, float] = {}
        self.pid = os.getpid()
        # epoch anchor for cross-process timeline alignment
        self.t0_epoch = time.time()

    # -- span API -------------------------------------------------------------

    def span(self, name: str, cat: str = "op",
             bucket: Optional[str] = None, collect: bool = False,
             **attrs):
        if not self.enabled:
            return NULL_SPAN
        return Span(self, name, cat, bucket, collect, attrs)

    def record(self, name: str, cat: str, t0_epoch: float,
               wall_s: float, **attrs) -> None:
        """Log an already-measured region (explicit timestamps) — for
        overlapping regions a context manager cannot express, e.g. the
        throughput wrapper's concurrent stream processes."""
        if not self.enabled:
            return
        self._append_event({
            "name": name, "cat": cat, "ph": "X",
            "ts_epoch_s": round(t0_epoch, 6),
            "wall_s": round(wall_s, 6),
            "pid": self.pid, "tid": threading.get_ident(),
            "seq": self._next_seq(), "depth": 0,
            "args": attrs,
        })

    def add_time(self, bucket: str, seconds: float) -> None:
        """Attribute seconds to a bucket of the innermost collector on
        this thread (or the process fallback) without opening a span."""
        if not self.enabled:
            return
        stack = self._stack()
        coll = stack[-1].collector if stack else self._fallback_collector
        if coll is not None and coll.buckets is not None:
            coll.buckets[bucket] = coll.buckets.get(bucket, 0.0) + seconds

    def annotate(self, **attrs) -> None:
        """Attach attributes to the innermost collector span on this
        thread (or the process fallback) without opening a span — e.g.
        the engine tagging the enclosing query span with the diagnostic
        code of a runtime fallback.  Last write per key wins; they
        surface in ``query_summaries()`` under ``attrs``."""
        if not self.enabled:
            return
        stack = self._stack()
        coll = stack[-1].collector if stack else self._fallback_collector
        if coll is not None:
            coll.attrs.update(attrs)

    # -- instruments ----------------------------------------------------------

    def inc(self, name: str, value: float = 1) -> None:
        if not self.enabled:
            return
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + value

    def set_gauge(self, name: str, value: float) -> None:
        if not self.enabled:
            return
        with self._lock:
            self.gauges[name] = value

    def counters_snapshot(self) -> Dict[str, float]:
        with self._lock:
            return dict(self.counters)

    def gauges_snapshot(self) -> Dict[str, float]:
        with self._lock:
            return dict(self.gauges)

    # -- internal -------------------------------------------------------------

    def _stack(self) -> List[Span]:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def _next_seq(self) -> int:
        with self._lock:
            self._seq += 1
            return self._seq

    def _finish(self, span: Span) -> None:
        ev = {
            "name": span.name, "cat": span.cat, "ph": "X",
            "ts_epoch_s": round(span.t0_epoch, 6),
            "wall_s": round(span.wall_s, 6),
            "pid": self.pid, "tid": span.tid,
            "seq": span.seq, "depth": span.depth,
            "args": span.attrs,
        }
        if span.bucket:
            ev["bucket"] = span.bucket
        if span.collect:
            ev["collect"] = True
            ev["buckets"] = {k: round(v, 6)
                             for k, v in span.buckets.items()}
        self._append_event(ev)

    def _append_event(self, ev: dict) -> None:
        with self._lock:
            self.events.append(ev)

    # -- aggregation ----------------------------------------------------------

    def query_summaries(self) -> List[dict]:
        """Finished collector spans of cat='query', with the cold/warm
        classification the HW metrics artifact is built from."""
        with self._lock:
            evs = [e for e in self.events
                   if e.get("collect") and e["cat"] == "query"]
        out = []
        for e in evs:
            b = e.get("buckets", {})
            wall = e["wall_s"]
            compile_s = b.get("compile_s", 0.0)
            execute_s = b.get("execute_s", 0.0)
            out.append({
                "query": e["name"],
                "wall_s": wall,
                "compile_s": round(compile_s, 6),
                "execute_s": round(execute_s, 6),
                "attributed_frac": round(
                    (compile_s + execute_s) / wall, 4) if wall > 0 else 0.0,
                # cold = compile work happened (discovery / jit build /
                # warm-up XLA compile); warm replays have ~zero compile
                "mode": "cold" if compile_s > max(0.05 * wall, 1e-4)
                        else "warm",
                "buckets": dict(b),
                "attrs": dict(e.get("args", {})),
            })
        return out
