"""Trace/metrics export: JSONL event log + Chrome ``trace_event`` JSON.

Two formats per run (tentpole contract):

* ``<base>.trace.jsonl`` — one JSON object per line: a ``meta`` header,
  one ``span`` line per finished span (epoch-anchored start, wall
  seconds, bucket totals on collectors), then ``counters`` and
  ``gauges`` lines.  Greppable, concatenable across processes.
* ``<base>.trace.json`` — ``{"traceEvents": [...]}`` with matched
  ``B``/``E`` duration events (µs timestamps), loadable in Perfetto /
  ``chrome://tracing``.  Span nesting renders as the flame stack.

``run_metrics(tracer)`` aggregates the same data into the dict the
harness writes as the ``<time_log>.metrics.json`` sidecar and
``docs/HW_METRICS_*.json`` embeds.
"""

from __future__ import annotations

import json
from typing import Optional

from ndstpu.obs.trace import Tracer


def export_jsonl(tracer: Tracer, path: str) -> str:
    # imported lazily: obs.__init__ -> export -> io.atomic -> faults ->
    # obs would be a bootstrap cycle at module-import time
    from ndstpu.io import atomic
    with tracer._lock:
        events = [dict(e) for e in tracer.events]
        counters = dict(tracer.counters)
        gauges = dict(tracer.gauges)
    with atomic.atomic_writer(path, "w") as f:
        f.write(json.dumps({"type": "meta", "format": "ndstpu-trace-v1",
                            "pid": tracer.pid,
                            "t0_epoch_s": tracer.t0_epoch}) + "\n")
        for e in events:
            f.write(json.dumps({"type": "span", **e}) + "\n")
        f.write(json.dumps({"type": "counters", "counters": counters})
                + "\n")
        f.write(json.dumps({"type": "gauges", "gauges": gauges}) + "\n")
    return path


def export_chrome(tracer: Tracer, path: str) -> str:
    """Perfetto-loadable trace: B/E pairs per span, µs epoch timestamps."""
    with tracer._lock:
        events = [dict(e) for e in tracer.events]
    out = []
    for e in events:
        ts = e["ts_epoch_s"] * 1e6
        dur = e["wall_s"] * 1e6
        base = {"name": e["name"], "cat": e["cat"],
                "pid": e["pid"], "tid": e["tid"]}
        args = dict(e.get("args", {}))
        if e.get("buckets"):
            args["buckets"] = e["buckets"]
        out.append({**base, "ph": "B", "ts": ts, "args": args})
        out.append({**base, "ph": "E", "ts": ts + dur})
    # B events at the same instant must open before they close; stable
    # sort on (ts, B-before-E at equal ts is wrong for zero-width spans
    # — keep pair adjacency by sorting on ts then original order)
    order = {id(e): i for i, e in enumerate(out)}
    out.sort(key=lambda e: (e["ts"], order[id(e)]))
    doc = {"traceEvents": out, "displayTimeUnit": "ms"}
    from ndstpu.io import atomic
    atomic.atomic_write_json(path, doc, indent=None)
    return path


def run_metrics(tracer: Tracer, extra: Optional[dict] = None) -> dict:
    """Aggregate a run: per-query attribution + instrument snapshot.

    ``queries[*].compile_s + execute_s`` over ``wall_s`` is the
    self-labeling cold/warm split; ``counters`` carries the cache
    hit/miss + exchange instruments."""
    queries = tracer.query_summaries()
    total_wall = sum(q["wall_s"] for q in queries)
    total_compile = sum(q["compile_s"] for q in queries)
    total_execute = sum(q["execute_s"] for q in queries)
    m = {
        "enabled": tracer.enabled,
        "queries": queries,
        "totals": {
            "n_queries": len(queries),
            "wall_s": round(total_wall, 6),
            "compile_s": round(total_compile, 6),
            "execute_s": round(total_execute, 6),
            "attributed_frac": round(
                (total_compile + total_execute) / total_wall, 4)
            if total_wall > 0 else 0.0,
            "cold_queries": sum(1 for q in queries
                                if q["mode"] == "cold"),
        },
        "counters": tracer.counters_snapshot(),
        "gauges": tracer.gauges_snapshot(),
    }
    if extra:
        m.update(extra)
    return m


def export_run(tracer: Tracer, directory: str, base: str) -> dict:
    """Write both trace formats under ``directory`` with stem ``base``;
    returns {'jsonl': path, 'chrome': path}."""
    import os
    os.makedirs(directory or ".", exist_ok=True)
    return {
        "jsonl": export_jsonl(
            tracer, os.path.join(directory, base + ".trace.jsonl")),
        "chrome": export_chrome(
            tracer, os.path.join(directory, base + ".trace.json")),
    }
