"""Regression sentinel: classify every query of a run vs the ledger.

After any power run, each measured query gets one verdict against its
best-known-warm ledger prior (ndstpu/obs/ledger.py):

* ``cold-compile`` — the tracer's compile/execute split says compile
  work happened (discovery / jit build / first XLA compile).  A first
  compile is **never** a regression, whatever the wall clock says;
  the verdict carries the ``execute_s`` split as the warm-path proxy
  so the run still contributes a baseline.
* ``new`` — no warm baseline exists for this (engine, scale-factor)
  scope; the run seeds one.
* ``data-changed`` — warm baselines exist for this scope but only
  under OTHER snapshot epochs (``extra.snapshot_epoch``, stamped from
  io/lake.warehouse_epoch): the warehouse's data-version vector moved
  (ingest/maintenance committed), so comparing walls would blame the
  engine for the data.  Never ``regressed``; the run seeds this
  epoch's baseline.  Entries with no stamp (legacy ledgers) stay
  comparable everywhere.
* ``regressed`` / ``improved`` — warm wall beyond both the relative
  tolerance and the absolute floor (both guards: a 0.1 s query
  jittering to 0.14 s is noise, not a regression).
* ``flat`` — within tolerance.
* ``failed`` — the query errored; excluded from baselines.  When the
  failure carries a taxonomy class (ndstpu/faults/taxonomy.py, stamped
  on the span by the retry layer as ``error_taxonomy``), the verdict is
  ``failed-transient`` or ``failed-permanent``; a failure that never
  went through the retry layer keeps the bare ``failed``.

A query that was served cached spine tables (``attrs.spine_hits``,
engine/spine.py) additionally carries ``warmth: "spine-warm"`` +
``spine_hits``/``spine_bytes_saved`` on its verdict: its wall against
the plain-warm baseline is the measured value of the spine cache, and
the matching ledger entries land under the ``spine-warm`` fingerprint
so they never become warm baselines themselves.

Only ``regressed`` verdicts are exit-code-worthy: the CLI wrapper
(scripts/regression_check.py) exits nonzero on genuine warm-path
regressions so CI and the bench driver both see them, and writes
``REGRESSIONS.json`` + a markdown table for the artifact trail.
"""

from __future__ import annotations

from typing import Iterable, List, Optional

from ndstpu.obs import ledger as ledger_mod

REL_TOL = 0.25      # regressed/improved only beyond +-25% ...
ABS_FLOOR_S = 0.25  # ... AND more than 0.25s absolute movement

VERDICTS = ("improved", "flat", "regressed", "cold-compile", "new",
            "data-changed", "failed", "failed-transient",
            "failed-permanent")


def classify_query(query: str, wall_s: float, compile_s: float,
                   execute_s: float, baseline_warm_s: Optional[float],
                   rel_tol: float = REL_TOL,
                   abs_floor_s: float = ABS_FLOOR_S) -> dict:
    """One verdict.  Cold-compile is decided FIRST, from the measured
    compile/execute split, so a first compile can never be flagged as
    a regression regardless of how slow the wall was."""
    out = {
        "query": query,
        "wall_s": round(wall_s, 6),
        "compile_s": round(compile_s, 6),
        "execute_s": round(execute_s, 6),
        "baseline_warm_s": None if baseline_warm_s is None
        else round(baseline_warm_s, 6),
    }
    if ledger_mod.derive_warmth(wall_s, compile_s) == "cold":
        out["verdict"] = "cold-compile"
        out["reason"] = (
            f"compile_s={compile_s:.3f}s of wall={wall_s:.3f}s is "
            f"first-compile work, not a warm-path cost; warm proxy "
            f"execute_s={execute_s:.3f}s"
            + (f" vs baseline {baseline_warm_s:.3f}s"
               if baseline_warm_s is not None else " (no baseline yet)"))
        return out
    if baseline_warm_s is None:
        out["verdict"] = "new"
        out["reason"] = "no warm baseline in ledger scope; seeding one"
        return out
    delta = wall_s - baseline_warm_s
    out["delta_s"] = round(delta, 6)
    out["ratio"] = round(wall_s / baseline_warm_s, 4) \
        if baseline_warm_s > 0 else None
    if delta > abs_floor_s and wall_s > baseline_warm_s * (1 + rel_tol):
        out["verdict"] = "regressed"
        out["reason"] = (f"warm wall {wall_s:.3f}s vs best-known-warm "
                         f"{baseline_warm_s:.3f}s (+{delta:.3f}s, "
                         f"x{out['ratio']})")
    elif -delta > abs_floor_s and \
            wall_s < baseline_warm_s * (1 - rel_tol):
        out["verdict"] = "improved"
        out["reason"] = (f"warm wall {wall_s:.3f}s vs best-known-warm "
                         f"{baseline_warm_s:.3f}s ({delta:.3f}s, "
                         f"x{out['ratio']})")
    else:
        out["verdict"] = "flat"
        out["reason"] = (f"within tolerance of best-known-warm "
                         f"{baseline_warm_s:.3f}s")
    return out


def classify_run(queries: Iterable[dict], led: "ledger_mod.Ledger",
                 engine: Optional[str] = None, scale_factor=None,
                 rel_tol: float = REL_TOL,
                 abs_floor_s: float = ABS_FLOOR_S,
                 snapshot_epoch: Optional[str] = None) -> dict:
    """Classify a run's per-query summaries (the power sidecar /
    ``query_summaries()`` shape: query, wall_s, compile_s, execute_s,
    optional attrs.error).  Baselines are scoped strictly to
    (engine, scale_factor) — cross-engine comparisons are meaningless —
    and, when ``snapshot_epoch`` is given, to entries of the same data
    epoch (or unstamped legacy entries); a query whose only warm
    baselines live under other epochs verdicts ``data-changed``."""
    verdicts: List[dict] = []
    for q in queries:
        name = q["query"]
        if (q.get("attrs") or {}).get("error"):
            attrs = q["attrs"]
            verdict = "failed"
            if attrs.get("error_taxonomy") in ("transient", "permanent"):
                verdict = f"failed-{attrs['error_taxonomy']}"
            v = {
                "query": name, "wall_s": round(q.get("wall_s", 0.0), 6),
                "verdict": verdict,
                "reason": f"query errored: {attrs['error']}",
            }
            if attrs.get("error_attempts"):
                v["attempts"] = attrs["error_attempts"]
            verdicts.append(v)
            continue
        base = led.best_warm(name, engine=engine,
                             scale_factor=scale_factor,
                             snapshot_epoch=snapshot_epoch)
        v = classify_query(
            name, q.get("wall_s", 0.0), q.get("compile_s", 0.0),
            q.get("execute_s", 0.0), base, rel_tol=rel_tol,
            abs_floor_s=abs_floor_s)
        if v["verdict"] == "new" and snapshot_epoch is not None:
            # no same-epoch baseline: distinguish genuinely-new from
            # data-changed (baselines exist, but under other epochs)
            others = led.warm_epochs(name, engine=engine,
                                     scale_factor=scale_factor)
            others.discard(snapshot_epoch)
            if others:
                v["verdict"] = "data-changed"
                v["reason"] = (
                    f"warm baselines exist only under other snapshot "
                    f"epoch(s) {sorted(others)} — the data changed "
                    f"under this query, not the engine; seeding epoch "
                    f"{snapshot_epoch}")
        # spine-warm is its own warmth class (ndstpu/obs/ledger.py):
        # a query served cached spine tables (engine/spine.py) skipped
        # its spine's scan/filter/join work, so its wall is measured
        # hit VALUE against the plain-warm baseline, never a new
        # baseline itself.  The stamp keeps that measurable per query
        # without widening the fixed VERDICTS set.
        attrs = q.get("attrs") or {}
        if attrs.get("spine_hits"):
            v["warmth"] = "cold" if v["verdict"] == "cold-compile" \
                else "spine-warm"
            v["spine_hits"] = attrs["spine_hits"]
            if attrs.get("spine_bytes_saved"):
                v["spine_bytes_saved"] = attrs["spine_bytes_saved"]
            v["reason"] += (f" [spine-warm: {attrs['spine_hits']} "
                            f"cached-spine hit(s)]")
        verdicts.append(v)
    counts: dict = {}
    for v in verdicts:
        counts[v["verdict"]] = counts.get(v["verdict"], 0) + 1
    return {
        "format": "ndstpu-regressions-v1",
        "engine": engine,
        "scale_factor": None if scale_factor is None
        else str(scale_factor),
        "rel_tol": rel_tol,
        "abs_floor_s": abs_floor_s,
        "counts": counts,
        "regressions": [v["query"] for v in verdicts
                        if v["verdict"] == "regressed"],
        "verdicts": verdicts,
    }


def markdown_table(result: dict) -> str:
    """REGRESSIONS.md body: one row per query, regressions first."""
    order = {"regressed": 0, "improved": 1, "new": 2,
             "data-changed": 3, "flat": 4, "cold-compile": 5,
             "failed": 6, "failed-transient": 7,
             "failed-permanent": 8}
    rows = sorted(result["verdicts"],
                  key=lambda v: (order.get(v["verdict"], 9), v["query"]))
    lines = [
        "# Regression sentinel",
        "",
        f"engine={result.get('engine')} "
        f"sf={result.get('scale_factor')} "
        f"counts={result.get('counts')}",
        "",
        "| query | wall_s | baseline_warm_s | delta_s | ratio | "
        "verdict |",
        "|---|---|---|---|---|---|",
    ]
    for v in rows:
        lines.append(
            "| {q} | {w} | {b} | {d} | {r} | {v} |".format(
                q=v["query"], w=v.get("wall_s", ""),
                b=v.get("baseline_warm_s", ""),
                d=v.get("delta_s", ""), r=v.get("ratio", ""),
                v=v["verdict"]))
    return "\n".join(lines) + "\n"


def write_reports(result: dict, json_path: Optional[str] = None,
                  md_path: Optional[str] = None) -> dict:
    from ndstpu.io import atomic
    paths = {}
    if json_path:
        atomic.atomic_write_json(json_path, result)
        paths["json"] = json_path
    if md_path:
        atomic.atomic_write_text(md_path, markdown_table(result))
        paths["md"] = md_path
    return paths
