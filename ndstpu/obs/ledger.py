"""Append-only per-query run ledger (JSONL) with fingerprint keying.

Every measured query execution lands here as one JSON line keyed by a
*fingerprint* — ``engine|sf<scale>|seed:<seed>|<warmth>`` — where
warmth is **measured, not asserted**: the tracer's ``compile_s`` /
``execute_s`` split (ndstpu/obs/trace.py) decides cold vs warm with
the same rule the BenchReport metrics block uses.  Round 5's headline
regressed from 2.56x to 0.60x because a cold re-baseline silently
burned the driver's budget; the ledger is the durable memory that
makes such a run *say so*: it serves two priors per query,

* **best-known-warm** — the fastest warm wall ever recorded.  Cold
  runs contribute their ``execute_s`` (a cold run's post-compile
  execution is the best available warm proxy), so a first-ever cold
  pass still seeds a baseline the next run can be judged against.
* **expected-cold** — the median cold wall (first-compile cost), the
  honest ETA when no warm artifacts exist.

Consumers: the harness heartbeat / cheapest-first budget degradation
(ndstpu/harness/progress.py) and the regression sentinel
(ndstpu/obs/sentinel.py, scripts/regression_check.py).

The file format is one self-describing dict per line (``v: 1``);
unreadable lines are counted and skipped, never fatal — an interrupted
append must not poison the history.  ``ingest_file`` understands the
legacy artifact shapes already in the tree (``BENCH_r0*.json`` driver
records, ``docs/WARM_R5_SF1.json`` discover/steady walls, and
``*.metrics.json`` power-run sidecars) so the pre-ledger history
serves priors from day one.
"""

from __future__ import annotations

import glob
import json
import os
import time
from typing import Dict, Iterable, List, Optional

LEDGER_ENV = "NDSTPU_LEDGER"
DEFAULT_RELPATH = os.path.join(".bench_cache", "ledger.jsonl")

# same threshold as the BenchReport metrics block / query_summaries():
# cold = compile work happened beyond clock noise
_COLD_FRAC = 0.05
_COLD_ABS_S = 1e-4


def default_path(root: str = ".") -> str:
    """Ledger location: $NDSTPU_LEDGER, else .bench_cache/ledger.jsonl."""
    return os.environ.get(LEDGER_ENV) or os.path.join(root, DEFAULT_RELPATH)


def derive_warmth(wall_s: float, compile_s: float) -> str:
    return "cold" if compile_s > max(_COLD_FRAC * wall_s, _COLD_ABS_S) \
        else "warm"


def fingerprint(engine: str, scale_factor, seed, warmth: str) -> str:
    return f"{engine}|sf{scale_factor}|seed:{seed}|{warmth}"


def make_entry(query: str, wall_s: float, compile_s: float = 0.0,
               execute_s: float = 0.0, engine: str = "unknown",
               scale_factor="unknown", seed="unknown",
               warmth: Optional[str] = None, source: str = "",
               ts: Optional[float] = None,
               extra: Optional[dict] = None) -> dict:
    """One ledger line.  ``warmth`` defaults to the measured
    compile/execute-split classification; pass it explicitly only for
    legacy artifacts that recorded the phase out of band (e.g. the
    warm-corpus discover/steady passes).

    A warm execution that was served cached spine tables
    (``extra.spine_hits`` > 0, engine/spine.py) is its own warmth
    class — ``spine-warm`` — because its wall is not comparable to a
    plain warm replay: it skipped the spine's scan/filter/join work
    entirely.  Keeping it out of the ``warm`` fingerprint means spine
    hits can never deflate ``best_warm`` baselines (and the sentinel
    can price the hit value explicitly)."""
    w = warmth or derive_warmth(wall_s, compile_s)
    if warmth is None and w == "warm" and extra and \
            extra.get("spine_hits"):
        w = "spine-warm"
    e = {
        "v": 1,
        "ts": round(time.time() if ts is None else ts, 3),
        "query": query,
        "engine": engine,
        "scale_factor": str(scale_factor),
        "seed": str(seed),
        "warmth": w,
        "wall_s": round(float(wall_s), 6),
        "compile_s": round(float(compile_s), 6),
        "execute_s": round(float(execute_s), 6),
        "fingerprint": fingerprint(engine, scale_factor, seed, w),
        "source": source,
    }
    if extra:
        e["extra"] = extra
    return e


def _dedupe_key(e: dict):
    return (e.get("source"), e.get("query"), e.get("warmth"),
            round(float(e.get("wall_s", 0.0)), 4))


class Ledger:
    """JSONL-backed run history.  ``path=None`` keeps it in memory only
    (selftest / read-only classification)."""

    def __init__(self, path: Optional[str] = None, load: bool = True):
        self.path = path
        self.entries: List[dict] = []
        self.corrupt_lines = 0
        self._seen = set()
        if path and load and os.path.exists(path):
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        e = json.loads(line)
                    except ValueError:
                        self.corrupt_lines += 1
                        continue
                    if isinstance(e, dict) and "query" in e:
                        self.entries.append(e)
                        self._seen.add(_dedupe_key(e))
                    else:
                        self.corrupt_lines += 1

    def __len__(self) -> int:
        return len(self.entries)

    # -- write ---------------------------------------------------------------

    def append(self, entries, dedupe: bool = False) -> int:
        """Append entry dict(s) to memory and (when backed) the file.
        ``dedupe=True`` skips entries already present under the
        (source, query, warmth, wall) key — re-ingesting the same
        artifact is then a no-op."""
        if isinstance(entries, dict):
            entries = [entries]
        added = []
        for e in entries:
            k = _dedupe_key(e)
            if dedupe and k in self._seen:
                continue
            self._seen.add(k)
            self.entries.append(e)
            added.append(e)
        if added and self.path:
            d = os.path.dirname(self.path)
            if d:
                os.makedirs(d, exist_ok=True)
            # durable append (flush+fsync): a kill right after a query
            # completes must not lose its ledger entry, or resume would
            # re-run it
            with open(self.path, "a") as f:
                for e in added:
                    f.write(json.dumps(e, sort_keys=True) + "\n")
                f.flush()
                os.fsync(f.fileno())
        return len(added)

    def record_query(self, query: str, wall_s: float, compile_s: float,
                     execute_s: float, **ctx) -> dict:
        e = make_entry(query, wall_s, compile_s, execute_s, **ctx)
        self.append(e)
        return e

    # -- priors --------------------------------------------------------------

    def _match(self, query: Optional[str] = None,
               engine: Optional[str] = None,
               scale_factor=None,
               warmth: Optional[str] = None) -> List[dict]:
        out = []
        for e in self.entries:
            if query is not None and e.get("query") != query:
                continue
            if engine is not None and e.get("engine") != engine:
                continue
            if scale_factor is not None and \
                    e.get("scale_factor") != str(scale_factor):
                continue
            if warmth is not None and e.get("warmth") != warmth:
                continue
            out.append(e)
        return out

    def best_warm(self, query: str, engine: Optional[str] = None,
                  scale_factor=None,
                  snapshot_epoch: Optional[str] = None
                  ) -> Optional[float]:
        """Fastest known warm wall.  Cold entries contribute their
        execute_s split — the post-compile execution is the warm proxy
        that lets a second run be judged against a first-ever cold one.

        With ``snapshot_epoch``, entries stamped with a DIFFERENT
        ``extra.snapshot_epoch`` (io/lake.warehouse_epoch) are excluded
        — a warm wall measured over other data is not a baseline for
        this data.  Unstamped (pre-epoch) entries still qualify, so
        legacy ledgers keep comparing until re-stamped."""
        def epoch_ok(e: dict) -> bool:
            if snapshot_epoch is None:
                return True
            ep = (e.get("extra") or {}).get("snapshot_epoch")
            return ep is None or ep == snapshot_epoch

        vals = [e["wall_s"] for e in self._match(query, engine,
                                                 scale_factor, "warm")
                if epoch_ok(e)]
        vals += [e["execute_s"] for e in self._match(query, engine,
                                                     scale_factor, "cold")
                 if e.get("execute_s", 0.0) > 1e-6 and epoch_ok(e)]
        return min(vals) if vals else None

    def warm_epochs(self, query: str, engine: Optional[str] = None,
                    scale_factor=None) -> set:
        """Distinct stamped snapshot epochs among this scope's
        baseline-eligible entries (warm walls + cold execute proxies)
        — the sentinel's data-changed detector."""
        out = set()
        for warmth in ("warm", "cold"):
            for e in self._match(query, engine, scale_factor, warmth):
                if warmth == "cold" and \
                        e.get("execute_s", 0.0) <= 1e-6:
                    continue
                ep = (e.get("extra") or {}).get("snapshot_epoch")
                if ep:
                    out.add(ep)
        return out

    def expected_cold(self, query: str, engine: Optional[str] = None,
                      scale_factor=None) -> Optional[float]:
        """Median cold wall — the first-compile cost prior."""
        vals = sorted(e["wall_s"] for e in self._match(query, engine,
                                                       scale_factor, "cold"))
        if not vals:
            return None
        n = len(vals)
        mid = n // 2
        return vals[mid] if n % 2 else (vals[mid - 1] + vals[mid]) / 2

    def estimate(self, query: str, engine: Optional[str] = None,
                 scale_factor=None, warmth: str = "warm",
                 default: Optional[float] = None) -> Optional[float]:
        """ETA prior for the heartbeat.  Unlike the sentinel baselines
        (strict scope), an estimate relaxes its scope — any history
        beats no history when projecting a deadline: exact
        (engine, sf) -> same engine any sf -> any engine."""
        for eng, sf in ((engine, scale_factor), (engine, None),
                        (None, None)):
            if warmth == "cold":
                v = self.expected_cold(query, eng, sf) or \
                    self.best_warm(query, eng, sf)
            else:
                v = self.best_warm(query, eng, sf) or \
                    self.expected_cold(query, eng, sf)
            if v is not None:
                return v
        return default

    def queries(self) -> set:
        return {e["query"] for e in self.entries}

    # -- legacy-artifact ingest ----------------------------------------------

    def ingest_file(self, path: str, engine: Optional[str] = None,
                    scale_factor=None, seed=None) -> int:
        """Sniff one artifact's shape and ingest it (deduped):

        * power-run sidecar (``run_metrics`` output): ``queries: [...]``
          with per-query wall/compile/execute + mode;
        * warm-corpus artifact (docs/WARM_R5_SF1.json): ``discover`` /
          ``steady`` name->seconds maps (cold / warm passes);
        * driver record (BENCH_r0*.json): ``cmd``/``rc`` + ``parsed``
          headline — kept as one run-level ``__bench__`` entry;
        * an existing ledger (JSONL) — merged line by line.
        """
        src = os.path.basename(path)
        with open(path) as f:
            text = f.read()
        try:
            obj = json.loads(text)
        except ValueError:
            obj = None
        entries: List[dict] = []
        if isinstance(obj, dict) and isinstance(obj.get("queries"), list):
            eng = engine or obj.get("engine", "unknown")
            for q in obj["queries"]:
                if not isinstance(q, dict) or "query" not in q:
                    continue
                entries.append(make_entry(
                    q["query"], q.get("wall_s", 0.0),
                    q.get("compile_s", 0.0), q.get("execute_s", 0.0),
                    engine=eng, scale_factor=scale_factor or "unknown",
                    seed=seed or "unknown",
                    warmth=q.get("mode"), source=src))
        elif isinstance(obj, dict) and ("discover" in obj or
                                        "steady" in obj):
            eng = engine or "tpu"
            sf = scale_factor or "unknown"
            sd = seed or "unknown"
            for q, wall in (obj.get("discover") or {}).items():
                entries.append(make_entry(
                    q, wall, compile_s=wall, engine=eng, scale_factor=sf,
                    seed=sd, warmth="cold", source=src))
            for q, wall in (obj.get("steady") or {}).items():
                entries.append(make_entry(
                    q, wall, execute_s=wall, engine=eng, scale_factor=sf,
                    seed=sd, warmth="warm", source=src))
        elif isinstance(obj, dict) and "cmd" in obj and "rc" in obj:
            parsed = obj.get("parsed") or {}
            entries.append(make_entry(
                "__bench__", parsed.get("elapsed_s", 0.0) or 0.0,
                engine=engine or "unknown",
                scale_factor=scale_factor or "unknown",
                seed=seed or "unknown", warmth="unknown", source=src,
                extra={k: parsed[k] for k in
                       ("metric", "value", "vs_baseline",
                        "geomean_speedup", "partial", "phase_reached")
                       if k in parsed} or None))
        elif obj is None:
            # maybe JSONL (another ledger): merge parseable lines
            for line in text.splitlines():
                line = line.strip()
                if not line:
                    continue
                try:
                    e = json.loads(line)
                except ValueError:
                    continue
                if isinstance(e, dict) and "query" in e:
                    entries.append(e)
        return self.append(entries, dedupe=True)

    def ingest_history(self, root: str = ".") -> Dict[str, int]:
        """Ingest the repo's committed history: BENCH_r0*.json driver
        records, the warm-corpus walls, and any power-run sidecars at
        the root / under docs.  Returns {path: entries added}."""
        counts: Dict[str, int] = {}
        for p in sorted(glob.glob(os.path.join(root, "BENCH_r*.json"))):
            counts[p] = self.ingest_file(p)
        warm = os.path.join(root, "docs", "WARM_R5_SF1.json")
        if os.path.exists(warm):
            counts[warm] = self.ingest_file(
                warm, engine="tpu", scale_factor="1", seed="bench")
        for pat in ("*.metrics.json", os.path.join("docs",
                                                   "*.metrics.json")):
            for p in sorted(glob.glob(os.path.join(root, pat))):
                counts[p] = self.ingest_file(p)
        return counts
