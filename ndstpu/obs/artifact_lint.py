"""Machine checks that committed docs and artifacts stay honest.

Two lints (CLI wrapper: scripts/doc_lint.py; wired into the test
suite via tests/test_doc_lint.py):

1. **Citation lint** — scan ``docs/*.md`` (and README.md / a root
   STATUS.md) for cited artifact paths (``docs/*.json``/``docs/*.csv``
   and root ``BENCH_*.json`` / ``PLAN_LINT.json`` / ``PLAN_LINT.md`` /
   ``CANON_AUDIT.json`` / ``CANON_AUDIT.md`` / ``MQO_AUDIT.json`` /
   ``MQO_AUDIT.md`` / ``DICT_AUDIT.json`` / ``DICT_AUDIT.md`` /
   ``COST_LINT.json`` / ``COST_LINT.md``)
   and fail when a cited file is absent
   from the tree.  A citation whose line carries an explicit
   not-here-yet marker (``pending``, ``uncommitted``,
   ``not committed``) is exempt — docs may *promise* an artifact, they
   may not *cite* a ghost.  ``RUN_STATE.json`` citations are
   recognized but exempt from the existence check: it is a per-run
   resume journal (docs/ROBUSTNESS.md), never a committed file.

2. **Config-mismatch lint** — a ``docs/*.json`` artifact may record
   the engine defaults it was measured under in a top-level
   ``engine_defaults`` map (e.g. ``{"NDSTPU_GROUPBY": "auto"}``).
   When a recorded default no longer matches the code's current
   default the artifact describes an engine that no longer exists;
   lint fails unless the artifact is stamped ``"stale": true`` (with
   ``describes_commit`` / ``stale_reason`` telling the reader what it
   does describe).  Current defaults are parsed from the engine
   *source* (jaxexec.py's ``GROUPBY_DEFAULT``), not imported —
   importing the engine pulls jax, and lint must run anywhere.
"""

from __future__ import annotations

import glob
import json
import os
import re
from typing import Dict, Iterable, List, Optional, Tuple

CITED_RE = re.compile(
    r"\bdocs/[A-Za-z0-9_.\-/]*\.(?:json|csv)\b"
    r"|\bBENCH_[A-Za-z0-9_.\-]*\.json\b"
    r"|\bPLAN_LINT\.(?:json|md)\b"
    r"|\bCANON_AUDIT\.(?:json|md)\b"
    r"|\bMQO_AUDIT\.(?:json|md)\b"
    r"|\bDICT_AUDIT\.(?:json|md)\b"
    r"|\bCOST_LINT\.(?:json|md)\b"
    r"|\bRUN_STATE\.json\b"
    r"|\bINGEST_DIFF\.json\b"
    r"|\bSLO\.json\b"
    r"|\bFLEET_HEALTH\.json\b")

EXEMPT_MARKERS = ("pending", "uncommitted", "not committed")

# recognized per-run journals/artifacts: docs cite these by name (they
# define the resume/differential/SLO/fleet-health contracts,
# docs/ROBUSTNESS.md and docs/OBSERVABILITY.md) but every run writes
# its own next to its artifacts — there is never a committed copy to
# point at
RUNTIME_ARTIFACTS = ("RUN_STATE.json", "INGEST_DIFF.json", "SLO.json",
                     "FLEET_HEALTH.json")

_GROUPBY_DEFAULT_RE = re.compile(
    r'^GROUPBY_DEFAULT\s*=\s*["\'](\w+)["\']', re.MULTILINE)


def cited_artifacts(text: str) -> Iterable[Tuple[int, str, str]]:
    """(lineno, cited path, line) for every artifact citation."""
    for lineno, line in enumerate(text.splitlines(), start=1):
        for m in CITED_RE.finditer(line):
            yield lineno, m.group(0), line


def lint_text(text: str, root: str, doc: str = "<doc>") -> List[str]:
    findings = []
    for lineno, path, line in cited_artifacts(text):
        low = line.lower()
        if any(mk in low for mk in EXEMPT_MARKERS):
            continue
        if os.path.basename(path) in RUNTIME_ARTIFACTS:
            continue
        if not os.path.exists(os.path.join(root, path)):
            findings.append(
                f"{doc}:{lineno}: cites missing artifact {path} "
                f"(commit it, or mark the citation 'pending')")
    return findings


def lint_docs(root: str = ".",
              docs: Optional[Iterable[str]] = None) -> List[str]:
    """Citation-lint the committed prose: docs/*.md, README.md, and a
    root-level STATUS.md when present."""
    if docs is None:
        docs = sorted(glob.glob(os.path.join(root, "docs", "*.md")))
        for extra in ("README.md", "STATUS.md"):
            p = os.path.join(root, extra)
            if os.path.exists(p):
                docs.append(p)
    findings: List[str] = []
    for p in docs:
        with open(p) as f:
            text = f.read()
        findings.extend(lint_text(text, root,
                                  doc=os.path.relpath(p, root)))
    return findings


def current_engine_defaults(root: str = ".") -> Dict[str, str]:
    """Defaults artifacts may pin themselves to, parsed from source so
    lint never needs to import jax."""
    src_path = os.path.join(root, "ndstpu", "engine", "jaxexec.py")
    out: Dict[str, str] = {}
    try:
        with open(src_path) as f:
            src = f.read()
    except OSError:
        return out
    m = _GROUPBY_DEFAULT_RE.search(src)
    if m:
        out["NDSTPU_GROUPBY"] = m.group(1)
    return out


def artifact_config_mismatches(
        root: str = ".",
        current: Optional[Dict[str, str]] = None) -> List[str]:
    current = current if current is not None \
        else current_engine_defaults(root)
    findings: List[str] = []
    for p in sorted(glob.glob(os.path.join(root, "docs", "*.json"))):
        try:
            with open(p) as f:
                obj = json.load(f)
        except (ValueError, OSError):
            continue
        if not isinstance(obj, dict):
            continue
        recorded = obj.get("engine_defaults")
        if not isinstance(recorded, dict) or obj.get("stale"):
            continue
        rel = os.path.relpath(p, root)
        for k, v in recorded.items():
            cur = current.get(k)
            if cur is not None and str(cur) != str(v):
                findings.append(
                    f"{rel}: measured under {k}={v} but the current "
                    f"default is {k}={cur} - regenerate the artifact "
                    f"or stamp it '\"stale\": true' with "
                    f"describes_commit/stale_reason")
    return findings


def lint_repo(root: str = ".") -> List[str]:
    """All lints; empty list means the committed tree is honest."""
    return lint_docs(root) + artifact_config_mismatches(root)
