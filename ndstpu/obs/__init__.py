"""Unified observability: span tracer + cost-attribution metrics.

The engine (``jaxexec``), SPMD executor (``dplan``/``exchange``), and
harness (``power``/``bench``/``report``) all instrument through this
package's module-level facade over one process-global tracer:

    from ndstpu import obs
    with obs.span("discovery", cat="plan-node", bucket="compile_s"):
        ...
    obs.inc("engine.cache.compiled.hit")

Default ON; ``NDSTPU_TRACE=0`` disables everything (spans become a
shared no-op, instruments early-return).  See docs/OBSERVABILITY.md for
the span model, instrument catalog, and export formats.
"""

from __future__ import annotations

from typing import Optional

from ndstpu.obs import export as _export
from ndstpu.obs.trace import NULL_SPAN, Span, Tracer, env_enabled

__all__ = [
    "Tracer", "Span", "NULL_SPAN", "env_enabled", "tracer", "enabled",
    "span", "record", "add_time", "annotate", "inc", "set_gauge",
    "counters_snapshot", "gauges_snapshot", "counter_delta",
    "export_jsonl", "export_chrome", "export_run", "run_metrics",
    "reset",
]

_TRACER = Tracer()


def tracer() -> Tracer:
    return _TRACER


def enabled() -> bool:
    return _TRACER.enabled


def reset(enabled: Optional[bool] = None) -> Tracer:
    """Replace the global tracer (tests / long-lived drivers starting a
    fresh measurement window).  Returns the new tracer."""
    global _TRACER
    _TRACER = Tracer(enabled=enabled)
    return _TRACER


def span(name: str, cat: str = "op", bucket: Optional[str] = None,
         collect: bool = False, **attrs):
    return _TRACER.span(name, cat=cat, bucket=bucket, collect=collect,
                        **attrs)


def record(name: str, cat: str, t0_epoch: float, wall_s: float,
           **attrs) -> None:
    _TRACER.record(name, cat, t0_epoch, wall_s, **attrs)


def add_time(bucket: str, seconds: float) -> None:
    _TRACER.add_time(bucket, seconds)


def annotate(**attrs) -> None:
    _TRACER.annotate(**attrs)


def inc(name: str, value: float = 1) -> None:
    _TRACER.inc(name, value)


def set_gauge(name: str, value: float) -> None:
    _TRACER.set_gauge(name, value)


def counters_snapshot() -> dict:
    return _TRACER.counters_snapshot()


def gauges_snapshot() -> dict:
    return _TRACER.gauges_snapshot()


def counter_delta(before: dict, after: Optional[dict] = None) -> dict:
    """Non-zero counter movement between two snapshots (after defaults
    to the live registry) — the per-query metrics block contract."""
    if after is None:
        after = _TRACER.counters_snapshot()
    out = {}
    for k, v in after.items():
        d = v - before.get(k, 0)
        if d:
            out[k] = d
    return out


def export_jsonl(path: str) -> str:
    return _export.export_jsonl(_TRACER, path)


def export_chrome(path: str) -> str:
    return _export.export_chrome(_TRACER, path)


def export_run(directory: str, base: str) -> dict:
    return _export.export_run(_TRACER, directory, base)


def run_metrics(extra: Optional[dict] = None) -> dict:
    return _export.run_metrics(_TRACER, extra)
