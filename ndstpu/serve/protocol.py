"""Serve wire protocol: length-prefixed JSON frames over a stream socket.

One frame is a 4-byte big-endian payload length followed by that many
bytes of UTF-8 JSON.  Framing (not newline-delimited JSON) so SQL text
may contain anything, and a half-written frame from a killed peer is
detected as a short read, never misparsed.

Requests are JSON objects with an ``op``:

``sql``
    ``{"op": "sql", "id": str, "sql": str, "tenant": str,
    "deadline_s": float?, "name": str?}`` — execute one statement.
    ``name`` routes the result to ``<output_prefix>/<name>`` on the
    server (the power-CLI writer, byte-identical artifacts); without
    it rows materialize server-side and only the row count returns.
``ping`` / ``health`` / ``ready`` / ``stats``
    liveness, full health doc, readiness flag, obs counter snapshot.
``probe``
    ``{"op": "probe", "id": str}`` — the fleet supervisor's
    liveness/readiness verb.  Answered at all times once the listener
    is bound (``bind_early`` servers answer it **before** readiness),
    returning ``{"probe": {"alive", "ready", "draining", "pid",
    "replica_id", "endpoints", "uptime_s", "aot", ...}}``.  Readiness
    flips only after warm-restart replay and the optional
    ``--aot_corpus`` full-corpus precompile complete, so a supervisor
    routing on ``ready`` never sends traffic to a cold replica.
``drain``
    begin graceful drain (lifecycle.py); responds before draining.

Both transports (AF_UNIX and TCP, serve/transport.py) carry these
frames unchanged — parity is byte-level, and ``MAX_FRAME_BYTES`` +
per-connection read timeouts bound what one peer can pin.

Responses carry ``status``: ``ok`` | ``error`` (+``taxonomy``,
``attempts``) | ``overloaded`` (+``retry_after_s``) | ``rejected``
(+``reason``) | ``draining`` — the typed load-shedding contract
clients key their retry policy on (docs/ROBUSTNESS.md).
"""

from __future__ import annotations

import json
import socket
import struct
from typing import Optional

# a frame bigger than this is a protocol error, not a request — bounds
# memory per connection before admission control even runs
MAX_FRAME_BYTES = 64 << 20

_LEN = struct.Struct(">I")


class ProtocolError(ValueError):
    """Malformed frame (oversized, truncated mid-frame, non-JSON)."""


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    """Read exactly n bytes; None on clean EOF at a frame boundary."""
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            if not buf:
                return None
            raise ProtocolError(
                f"connection closed mid-frame ({len(buf)}/{n} bytes)")
        buf.extend(chunk)
    return bytes(buf)


def send_msg(sock: socket.socket, obj: dict) -> None:
    payload = json.dumps(obj, default=str).encode("utf-8")
    if len(payload) > MAX_FRAME_BYTES:
        raise ProtocolError(f"frame too large: {len(payload)} bytes")
    sock.sendall(_LEN.pack(len(payload)) + payload)


def recv_msg(sock: socket.socket) -> Optional[dict]:
    """One frame as a dict; None on clean EOF (peer hung up)."""
    header = _recv_exact(sock, _LEN.size)
    if header is None:
        return None
    (length,) = _LEN.unpack(header)
    if length > MAX_FRAME_BYTES:
        raise ProtocolError(f"frame too large: {length} bytes")
    payload = _recv_exact(sock, length)
    if payload is None:
        raise ProtocolError("connection closed between header and payload")
    try:
        obj = json.loads(payload.decode("utf-8"))
    except ValueError as e:
        raise ProtocolError(f"bad JSON frame: {e}") from e
    if not isinstance(obj, dict):
        raise ProtocolError(f"frame must be a JSON object, got "
                            f"{type(obj).__name__}")
    return obj
