"""Serve robustness control plane: journal, warm restart, drain, SLO.

**Journal** (``serve_journal.jsonl``, RUN_STATE idiom via
``atomic.append_jsonl``): ``server-start`` on boot, one ``query``
record per *successful* request (SQL text + canonical fingerprint),
``clean-shutdown`` at the end of a graceful drain.  Torn trailing
lines from a SIGKILL are tolerated by ``read_jsonl``.

**Warm restart**: on boot with an existing journal, the server (1)
preloads the compile-record set the previous incarnation persisted
incrementally (``Session.preload_compiled`` — records register under
canonical keys), (2) replays the journaled SQL texts through
``Session.canonical_key`` so the plan cache re-warms, and only then
(3) flips readiness.  A previously-seen plan shape served by the
restarted process executes with ZERO new compiles
(``engine.cache.compiled.miss`` stays flat — the serve_smoke proof).

**Drain** (SIGTERM): stop admission (new SQL answers ``draining``),
let in-flight queries finish (a hung one is abandoned via the power
watchdog idiom, never blocking shutdown), flush ledger + compile
records + ``SLO.json``, then journal the clean-shutdown marker.

**SLO**: per-tenant latency reservoirs export p50/p95/p99 to
``SLO.json`` (a runtime artifact like RUN_STATE.json — recognized by
artifact_lint, never committed).
"""

from __future__ import annotations

import signal
import threading
import time
from typing import Dict, List, Optional

from ndstpu import obs
from ndstpu.io import atomic

JOURNAL_START = "server-start"
JOURNAL_QUERY = "query"
JOURNAL_CLEAN = "clean-shutdown"

SLO_ARTIFACT = "ndstpu-slo-v1"


class ServeJournal:
    """Append-only lifecycle journal (one JSON record per line)."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        # dedup replay work: a SQL text journaled once is enough
        self._seen: set = set()

    def records(self) -> List[dict]:
        return atomic.read_jsonl(self.path)

    def mark_start(self, meta: Optional[dict] = None) -> None:
        rec = {"event": JOURNAL_START, "t": round(time.time(), 3)}
        rec.update(meta or {})
        with self._lock:
            atomic.append_jsonl(self.path, rec)

    def mark_query(self, name: str, sql: str,
                   canon_key: Optional[str] = None) -> None:
        with self._lock:
            if sql in self._seen:
                return
            self._seen.add(sql)
            atomic.append_jsonl(self.path, {
                "event": JOURNAL_QUERY, "name": name, "sql": sql,
                "canon_key": canon_key, "t": round(time.time(), 3)})

    def mark_clean_shutdown(self, meta: Optional[dict] = None) -> None:
        rec = {"event": JOURNAL_CLEAN, "t": round(time.time(), 3)}
        rec.update(meta or {})
        with self._lock:
            atomic.append_jsonl(self.path, rec)

    def replay_state(self) -> dict:
        """What a restart inherits: the journaled SQL set and whether
        the previous incarnation shut down cleanly (the last lifecycle
        event decides — a start after a clean marker means a crash)."""
        sqls: List[dict] = []
        seen: set = set()
        clean = True  # no journal at all = first boot, trivially clean
        for rec in self.records():
            ev = rec.get("event")
            if ev == JOURNAL_START:
                clean = False
            elif ev == JOURNAL_CLEAN:
                clean = True
            elif ev == JOURNAL_QUERY and rec.get("sql") and \
                    rec["sql"] not in seen:
                seen.add(rec["sql"])
                sqls.append(rec)
        self._seen |= seen
        return {"sqls": sqls, "clean": clean}


def warm_restart(session, journal: ServeJournal,
                 compile_records: Optional[str] = None,
                 out=print) -> dict:
    """Replay the journal + compile records into a fresh session BEFORE
    the server flips readiness.  Defects degrade to a cold start —
    warmth is an optimization, recovery must never fail the boot."""
    state = journal.replay_state()
    preloaded = 0
    if compile_records:
        try:
            preloaded = session.preload_compiled(compile_records)
        except Exception as e:  # noqa: BLE001
            out(f"WARNING: serve compile records not preloaded: {e}")
    replayed = 0
    for rec in state["sqls"]:
        try:
            # canonical_key plans the text (plan cache + canonical
            # registration) without executing it — AOT warmth for the
            # fingerprint set the previous incarnation served
            session.canonical_key(rec["sql"])
            replayed += 1
        except Exception as e:  # noqa: BLE001
            out(f"WARNING: journal replay skipped {rec.get('name')}: "
                f"{e}")
    obs.inc("serve.restart.preloaded_records", preloaded)
    obs.inc("serve.restart.replayed_sql", replayed)
    if not state["clean"]:
        obs.inc("serve.restart.after_crash")
    return {"preloaded": preloaded, "replayed": replayed,
            "clean_shutdown": state["clean"],
            "journaled": len(state["sqls"])}


class SLOTracker:
    """Per-tenant latency reservoirs -> p50/p95/p99 in ``SLO.json``."""

    def __init__(self, max_samples_per_tenant: int = 4096):
        self.max_samples = max_samples_per_tenant
        self._lock = threading.Lock()
        self._lat_ms: Dict[str, List[float]] = {}
        self._counts: Dict[str, Dict[str, int]] = {}
        self.started_epoch_s = round(time.time(), 3)

    def record(self, tenant: str, wall_s: float,
               outcome: str = "ok") -> None:
        with self._lock:
            c = self._counts.setdefault(
                tenant, {"ok": 0, "error": 0, "overloaded": 0,
                         "rejected": 0})
            c[outcome] = c.get(outcome, 0) + 1
            if outcome == "ok":
                lats = self._lat_ms.setdefault(tenant, [])
                lats.append(wall_s * 1000.0)
                if len(lats) > self.max_samples:
                    # keep the newest window; SLOs describe current
                    # behavior, not the whole process lifetime
                    del lats[:len(lats) - self.max_samples]

    @staticmethod
    def _pct(sorted_ms: List[float], p: float) -> float:
        if not sorted_ms:
            return 0.0
        idx = min(len(sorted_ms) - 1,
                  max(0, int(round(p / 100.0 * (len(sorted_ms) - 1)))))
        return sorted_ms[idx]

    def snapshot(self) -> dict:
        with self._lock:
            tenants = {}
            for tenant, counts in sorted(self._counts.items()):
                lats = sorted(self._lat_ms.get(tenant, ()))
                tenants[tenant] = {
                    "count": sum(counts.values()),
                    "ok": counts.get("ok", 0),
                    "error": counts.get("error", 0),
                    "overloaded": counts.get("overloaded", 0),
                    "rejected": counts.get("rejected", 0),
                    "p50_ms": round(self._pct(lats, 50), 3),
                    "p95_ms": round(self._pct(lats, 95), 3),
                    "p99_ms": round(self._pct(lats, 99), 3),
                }
        return {"artifact": SLO_ARTIFACT,
                "window_started_epoch_s": self.started_epoch_s,
                "exported_epoch_s": round(time.time(), 3),
                "tenants": tenants}

    def export(self, path: str) -> dict:
        doc = self.snapshot()
        atomic.atomic_write_json(path, doc)
        return doc


def install_signal_handlers(server) -> None:
    """SIGTERM/SIGINT -> graceful drain.  The handler only flags; the
    drain itself runs on a dedicated thread so signal context stays
    async-signal-safe-ish and a hung in-flight query cannot wedge the
    handler (the watchdog abandons it)."""
    def _handler(signum, frame):  # noqa: ARG001
        threading.Thread(target=server.drain,
                         kwargs={"reason": signal.Signals(signum).name},
                         name="serve-drain", daemon=True).start()

    signal.signal(signal.SIGTERM, _handler)
    signal.signal(signal.SIGINT, _handler)
