"""Replicated serving fleet: N QueryServer replicas over one warehouse.

The PR 14 server is one process — a single point of failure between
clients and the warehouse.  This module runs N replica processes
(each its own Session; all sharing the lake snapshots, global-dict
sidecars, and ONE incrementally-persisted compile-record file, so a
replica boot is zero-new-compiles on any shape the fleet has seen)
behind a **fleet supervisor**:

* **health loop** — each replica is probed over the wire (the
  ``probe`` verb, serve/protocol.py) every ``probe_interval_s``; the
  ``fleet.probe`` fault site sits in the probe path so chaos runs can
  exercise false-negative handling (a probe must fail
  ``probe_fail_threshold`` times consecutively, or the process must
  exit, before the supervisor declares death);
* **bounded-backoff restart** — a dead replica is SIGKILL-fenced,
  its stale ``COMMIT.lock`` leases under the warehouse broken (the
  PR 12 CAS protocol: a lock naming a dead pid can never commit), and
  relaunched after a doubling, capped backoff;
* **rolling zero-downtime restart** — :meth:`rolling_restart` drains
  one replica (graceful SIGTERM semantics via the ``drain`` verb),
  waits for its successor to probe ready, then moves to the next.
  Clients failover to siblings meanwhile (serve/client.py), so the
  invariant is zero dropped queries, at most one retry per client per
  restart;
* **re-adoption** — supervisor state is the probe state: on boot the
  supervisor probes every configured endpoint and ADOPTS live
  replicas (recording their pids) instead of double-starting them, so
  SIGKILL-ing the supervisor itself never interrupts serving (chaos
  scenario I).

Every loop iteration atomically rewrites ``FLEET_HEALTH.json`` in the
run dir — a runtime artifact (never committed; artifact_lint exempts
it like ``RUN_STATE.json``) that smoke tests and operators read for
pids, readiness, restart counts, and the serve.fleet.* counters.

``NDSTPU_FLEET=0`` is the kill switch: the supervisor degenerates to
one replica, and the plain single-server ``ndstpu-serve`` path is
untouched by this module entirely.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
from typing import Callable, Dict, List, Optional

from ndstpu import faults, obs
from ndstpu.io import commit as commit_mod
from ndstpu.serve import protocol, transport

FLEET_HEALTH_BASENAME = "FLEET_HEALTH.json"
FLEET_HEALTH_ARTIFACT = "ndstpu-fleet-health-v1"
FLEET_ENV = "NDSTPU_FLEET"


@dataclasses.dataclass
class FleetConfig:
    input_prefix: str
    replicas: int = 2
    run_dir: str = "fleet_state"
    endpoints: Optional[List[str]] = None  # default: stable unix socks
    engine: str = "cpu"
    output_prefix: Optional[str] = None
    output_format: str = "csv"
    compile_records: Optional[str] = None  # SHARED across replicas
    ledger_path: Optional[str] = "none"
    scale_factor: str = "unknown"
    floats: bool = False
    slots: int = 1
    queue_depth: Optional[int] = 64        # None -> memplan auto
    aot_corpus: Optional[str] = None
    query_timeout_s: Optional[float] = None
    probe_interval_s: float = 0.5
    probe_timeout_s: float = 5.0
    probe_fail_threshold: int = 3
    boot_grace_s: float = 120.0     # probe failures don't kill a boot
    restart_backoff_s: float = 0.25
    restart_backoff_max_s: float = 5.0
    ready_timeout_s: float = 600.0
    python: str = sys.executable


class _Replica:
    """Supervisor-side view of one replica process."""

    def __init__(self, replica_id: str, endpoint: str, state_dir: str):
        self.replica_id = replica_id
        self.endpoint = endpoint
        self.state_dir = state_dir
        self.proc: Optional[subprocess.Popen] = None
        self.pid: Optional[int] = None     # known pid (owned or adopted)
        self.adopted = False
        self.state = "down"  # down|starting|ready|restarting|draining
        self.ready = False
        self.restarts = 0
        self.consecutive_failures = 0
        self.backoff_s = 0.0
        self.launched_at: Optional[float] = None  # monotonic
        self.last_probe: Optional[dict] = None
        self.last_probe_at: Optional[float] = None
        self.last_exit: Optional[int] = None

    def doc(self) -> dict:
        return {"replica_id": self.replica_id,
                "endpoint": self.endpoint,
                "pid": self.pid,
                "adopted": self.adopted,
                "state": self.state,
                "ready": self.ready,
                "restarts": self.restarts,
                "consecutive_failures": self.consecutive_failures,
                "last_probe_at": self.last_probe_at,
                "last_exit": self.last_exit}


def default_endpoints(run_dir: str, n: int) -> List[str]:
    """Stable short AF_UNIX paths for a run dir: stable so a restarted
    supervisor probes the SAME sockets it (or its predecessor) bound —
    re-adoption depends on it — and short because unix socket paths
    cap at ~108 bytes regardless of where run_dir lives."""
    tag = hashlib.sha256(
        os.path.abspath(run_dir).encode()).hexdigest()[:8]
    base = tempfile.gettempdir()
    return [os.path.join(base, f"ndstpu-fleet-{tag}-r{i}.sock")
            for i in range(n)]


class FleetSupervisor:
    """Health-checks, restarts, and rolls N serve replicas."""

    def __init__(self, config: FleetConfig,
                 probe_fn: Optional[Callable] = None,
                 launcher: Optional[Callable] = None):
        self.config = config
        if os.environ.get(FLEET_ENV, "") == "0":
            print(f"[fleet] {FLEET_ENV}=0: degenerating to 1 replica")
            config = dataclasses.replace(config, replicas=1)
            self.config = config
        if config.replicas < 1:
            raise ValueError("fleet needs >= 1 replica")
        self._probe_fn = probe_fn or self._probe_rpc
        self._launcher = launcher or self._launch_proc
        os.makedirs(config.run_dir, exist_ok=True)
        self.shared_records = config.compile_records or os.path.join(
            config.run_dir, "compile_records.json")
        eps = (list(config.endpoints) if config.endpoints
               else default_endpoints(config.run_dir, config.replicas))
        if len(eps) != config.replicas:
            raise ValueError(f"{config.replicas} replicas need "
                             f"{config.replicas} endpoints, got "
                             f"{len(eps)}")
        self.replicas = [
            _Replica(f"r{i}", ep,
                     os.path.join(config.run_dir, f"r{i}"))
            for i, ep in enumerate(eps)]
        self.health_path = os.path.join(config.run_dir,
                                        FLEET_HEALTH_BASENAME)
        self._lock = threading.RLock()
        self._rolling_lock = threading.Lock()
        self._stopped = threading.Event()
        self._drained = threading.Event()  # drain_fleet finished
        self._monitor: Optional[threading.Thread] = None

    # -- wire helpers --------------------------------------------------------

    def _rpc(self, endpoint: str, msg: dict) -> dict:
        sock = transport.connect(
            endpoint, connect_timeout_s=self.config.probe_timeout_s,
            read_timeout_s_override=self.config.probe_timeout_s)
        try:
            protocol.send_msg(sock, msg)
            resp = protocol.recv_msg(sock)
            if resp is None:
                raise ConnectionResetError(
                    f"{endpoint}: closed during rpc")
            return resp
        finally:
            try:
                sock.close()
            except OSError:
                pass

    def _probe_rpc(self, rep: _Replica) -> dict:
        faults.check("fleet.probe", key=rep.replica_id)
        resp = self._rpc(rep.endpoint,
                         {"op": "probe", "id": f"fleet-{rep.replica_id}"})
        probe = resp.get("probe")
        if not isinstance(probe, dict):
            raise protocol.ProtocolError(
                f"{rep.endpoint}: probe verb unsupported: {resp}")
        return probe

    # -- launch / adopt / fence ----------------------------------------------

    def _launch_proc(self, rep: _Replica) -> subprocess.Popen:
        cfg = self.config
        os.makedirs(rep.state_dir, exist_ok=True)
        argv = [cfg.python, "-m", "ndstpu.harness.serve", "server",
                "--socket", rep.endpoint,
                "--input_prefix", cfg.input_prefix,
                "--engine", cfg.engine,
                "--output_format", cfg.output_format,
                "--state_dir", rep.state_dir,
                "--compile_records", self.shared_records,
                "--scale_factor", str(cfg.scale_factor),
                "--slots", str(cfg.slots),
                "--replica_id", rep.replica_id,
                "--bind_early"]
        argv += ["--queue_depth",
                 "auto" if not cfg.queue_depth else str(cfg.queue_depth)]
        if cfg.output_prefix:
            argv += ["--output_prefix", cfg.output_prefix]
        if cfg.ledger_path:
            argv += ["--ledger", cfg.ledger_path]
        if cfg.aot_corpus:
            argv += ["--aot_corpus", cfg.aot_corpus]
        if cfg.floats:
            argv += ["--floats"]
        if cfg.query_timeout_s is not None:
            argv += ["--query_timeout_s", str(cfg.query_timeout_s)]
        log = open(os.path.join(cfg.run_dir,
                                f"{rep.replica_id}.log"), "ab")
        try:
            # own session: replicas outlive a SIGKILL'd supervisor
            # (chaos scenario I) and never see its terminal signals
            return subprocess.Popen(argv, stdout=log, stderr=log,
                                    start_new_session=True)
        finally:
            log.close()

    def _fence(self, rep: _Replica) -> int:
        """Break the dead replica's stale CAS commit leases: any
        ``COMMIT.lock`` under the warehouse (or output root) naming
        its pid — or any pid that no longer exists — can never commit
        and would otherwise stall writers for a full lease."""
        dead_pid = rep.pid
        roots = [self.config.input_prefix]
        if self.config.output_prefix:
            roots.append(self.config.output_prefix)
        broken = 0
        for root in roots:
            if not root or not os.path.isdir(root):
                continue
            for dirpath, _dirnames, filenames in os.walk(root):
                if commit_mod.LOCK_BASENAME not in filenames:
                    continue
                path = os.path.join(dirpath, commit_mod.LOCK_BASENAME)
                try:
                    with open(path) as f:
                        holder = json.load(f)
                    pid = int(holder.get("pid", -1))
                except (OSError, ValueError):
                    pid = -1
                stale = pid == dead_pid or not _pid_alive(pid)
                if stale:
                    try:
                        os.unlink(path)
                        broken += 1
                    except OSError:
                        pass
        if broken:
            obs.inc("serve.fleet.fenced", broken)
            print(f"[fleet] fenced {broken} stale commit lease(s) "
                  f"left by {rep.replica_id} (pid {dead_pid})")
        return broken

    def _start_replica(self, rep: _Replica) -> None:
        rep.proc = self._launcher(rep)
        rep.pid = rep.proc.pid if rep.proc is not None else rep.pid
        rep.adopted = False
        rep.state = "starting"
        rep.ready = False
        rep.consecutive_failures = 0
        rep.launched_at = time.monotonic()
        rep.last_probe = None  # this incarnation has not probed yet
        rep.last_exit = None
        obs.inc("serve.fleet.launched")
        print(f"[fleet] launched {rep.replica_id} pid={rep.pid} "
              f"on {rep.endpoint}")

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        """Adopt live replicas (probe state is the source of truth —
        a restarted supervisor must never double-start), launch the
        rest, then begin the health loop."""
        for rep in self.replicas:
            probe = None
            try:
                probe = self._probe_fn(rep)
            except Exception:  # noqa: BLE001 — not running: launch it
                probe = None
            if probe and probe.get("alive"):
                rep.pid = probe.get("pid")
                rep.adopted = True
                rep.proc = None
                rep.ready = bool(probe.get("ready"))
                rep.state = "ready" if rep.ready else "starting"
                rep.last_probe = probe
                rep.last_probe_at = time.time()
                obs.inc("serve.fleet.adopted")
                print(f"[fleet] adopted live {rep.replica_id} "
                      f"pid={rep.pid} on {rep.endpoint} "
                      f"(ready={rep.ready})")
            else:
                self._start_replica(rep)
        self._write_health()
        self._monitor = threading.Thread(target=self._monitor_loop,
                                         name="fleet-monitor",
                                         daemon=True)
        self._monitor.start()
        obs.inc("serve.fleet.started")

    def wait_ready(self, timeout_s: Optional[float] = None) -> bool:
        """Block until every replica probes ready."""
        timeout_s = (self.config.ready_timeout_s
                     if timeout_s is None else timeout_s)
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline and not self._stopped.is_set():
            if all(r.ready for r in self.replicas):
                return True
            time.sleep(0.1)
        return all(r.ready for r in self.replicas)

    def endpoints_spec(self) -> str:
        """The comma-separated failover spec clients connect with."""
        return ",".join(r.endpoint for r in self.replicas)

    # -- health loop ---------------------------------------------------------

    def _monitor_loop(self) -> None:
        while not self._stopped.is_set():
            for rep in self.replicas:
                if self._stopped.is_set():
                    break
                with self._lock:
                    if rep.state in ("draining", "restarting"):
                        continue  # rolling_restart owns it right now
                    self._check_one(rep)
            self._write_health()
            self._stopped.wait(self.config.probe_interval_s)
        self._write_health()

    def _check_one(self, rep: _Replica) -> None:
        # process exit is authoritative death, no threshold needed
        if rep.proc is not None:
            rc = rep.proc.poll()
            if rc is not None:
                rep.last_exit = rc
                print(f"[fleet] {rep.replica_id} pid={rep.pid} "
                      f"exited rc={rc}")
                self._restart(rep)
                return
        try:
            probe = self._probe_fn(rep)
            obs.inc("serve.fleet.probes")
            rep.last_probe = probe
            rep.last_probe_at = time.time()
            rep.consecutive_failures = 0
            rep.backoff_s = 0.0
            was_ready = rep.ready
            rep.ready = bool(probe.get("ready"))
            rep.state = "ready" if rep.ready else "starting"
            if rep.adopted and probe.get("pid"):
                rep.pid = probe.get("pid")
            if rep.ready and not was_ready:
                print(f"[fleet] {rep.replica_id} ready "
                      f"(pid={rep.pid})")
        except Exception as e:  # noqa: BLE001 — probe failure
            obs.inc("serve.fleet.probe_failures")
            # a fresh incarnation hasn't bound yet: imports + catalog
            # load take seconds, so failed probes inside the boot
            # grace window are expected, not a death signal (process
            # exit above stays authoritative either way)
            booting = (rep.last_probe is None
                       and rep.launched_at is not None
                       and time.monotonic() - rep.launched_at
                       < self.config.boot_grace_s)
            if booting:
                return
            rep.consecutive_failures += 1
            if rep.consecutive_failures >= \
                    self.config.probe_fail_threshold:
                print(f"[fleet] {rep.replica_id} failed "
                      f"{rep.consecutive_failures} probes "
                      f"({type(e).__name__}: {e}); restarting")
                self._restart(rep)

    def _restart(self, rep: _Replica) -> None:
        """Fence + relaunch one dead replica with bounded backoff."""
        rep.state = "restarting"
        rep.ready = False
        obs.inc("serve.fleet.restarts")
        self._kill_quietly(rep)
        self._fence(rep)
        rep.backoff_s = min(
            max(rep.backoff_s * 2, self.config.restart_backoff_s),
            self.config.restart_backoff_max_s)
        rep.restarts += 1
        time.sleep(rep.backoff_s)
        self._start_replica(rep)

    def _kill_quietly(self, rep: _Replica) -> None:
        """Make sure the old incarnation is really gone before the new
        one binds its endpoint (idempotent on an already-dead pid)."""
        if rep.proc is not None:
            if rep.proc.poll() is None:
                try:
                    rep.proc.kill()
                except OSError:
                    pass
            try:
                rep.proc.wait(timeout=10)
                rep.last_exit = rep.proc.returncode
            except Exception:  # noqa: BLE001
                pass
            rep.proc = None
        elif rep.pid:
            try:
                os.kill(rep.pid, signal.SIGKILL)
            except OSError:
                pass

    # -- rolling restart -----------------------------------------------------

    def rolling_restart(self, reason: str = "rolling") -> dict:
        """Zero-downtime restart: drain + relaunch one replica at a
        time, waiting for it to probe ready before touching the next,
        so N-1 replicas serve at every instant."""
        if not self._rolling_lock.acquire(blocking=False):
            return {"skipped": "rolling restart already in progress"}
        try:
            obs.inc("serve.fleet.rolling_restarts")
            print(f"[fleet] rolling restart ({reason})")
            rolled = []
            for rep in self.replicas:
                with self._lock:
                    rep.state = "draining"
                    rep.ready = False
                self._drain_one(rep)
                with self._lock:
                    self._fence(rep)
                    rep.restarts += 1
                    self._start_replica(rep)
                if not self._wait_replica_ready(rep):
                    print(f"WARNING: [fleet] {rep.replica_id} not "
                          f"ready after rolling relaunch; continuing")
                rolled.append(rep.replica_id)
            print(f"[fleet] rolling restart complete: {rolled}")
            return {"rolled": rolled}
        finally:
            self._rolling_lock.release()

    def _drain_one(self, rep: _Replica) -> None:
        """SIGTERM-equivalent graceful drain over the wire; escalate
        to kill only if the drain wedges."""
        try:
            self._rpc(rep.endpoint,
                      {"op": "drain", "id": f"fleet-{rep.replica_id}"})
        except Exception as e:  # noqa: BLE001 — already dead is fine
            print(f"[fleet] {rep.replica_id} drain rpc failed "
                  f"({type(e).__name__}); treating as down")
        deadline = time.monotonic() + max(
            30.0, (self.config.query_timeout_s or 300.0) + 60.0)
        while time.monotonic() < deadline:
            if rep.proc is not None:
                if rep.proc.poll() is not None:
                    rep.last_exit = rep.proc.returncode
                    rep.proc = None
                    return
            else:
                if not rep.pid or not _pid_alive(rep.pid):
                    return
            time.sleep(0.1)
        print(f"WARNING: [fleet] {rep.replica_id} did not exit after "
              f"drain; killing")
        self._kill_quietly(rep)

    def _wait_replica_ready(self, rep: _Replica) -> bool:
        deadline = time.monotonic() + self.config.ready_timeout_s
        while time.monotonic() < deadline:
            try:
                probe = self._probe_fn(rep)
                rep.last_probe = probe
                rep.last_probe_at = time.time()
                if probe.get("ready"):
                    with self._lock:
                        rep.ready = True
                        rep.state = "ready"
                        rep.consecutive_failures = 0
                    return True
            except Exception:  # noqa: BLE001 — still booting
                pass
            if rep.proc is not None and rep.proc.poll() is not None:
                return False  # crashed during boot; monitor restarts
            time.sleep(0.2)
        return False

    # -- drain / health artifact ---------------------------------------------

    def drain_fleet(self, reason: str = "drain") -> dict:
        """Stop monitoring, drain every replica, record final state."""
        if self._stopped.is_set():
            return {"reason": reason, "already": True}
        self._stopped.set()
        if self._monitor is not None:
            self._monitor.join(timeout=self.config.probe_interval_s
                               + self.config.probe_timeout_s + 5)
        for rep in self.replicas:
            rep.state = "draining"
            rep.ready = False
            self._drain_one(rep)
            rep.state = "down"
        self._write_health()
        obs.inc("serve.fleet.drained")
        print(f"[fleet] drained ({reason})")
        self._drained.set()
        return {"reason": reason,
                "replicas": [r.replica_id for r in self.replicas]}

    def fleet_counters(self) -> Dict[str, float]:
        return {k: v for k, v in obs.counters_snapshot().items()
                if k.startswith("serve.fleet.")}

    def health_doc(self) -> dict:
        with self._lock:
            return {
                "artifact": FLEET_HEALTH_ARTIFACT,
                "supervisor_pid": os.getpid(),
                "updated_epoch_s": time.time(),
                "run_dir": os.path.abspath(self.config.run_dir),
                "input_prefix": self.config.input_prefix,
                "engine": self.config.engine,
                "endpoints": self.endpoints_spec(),
                "shared_compile_records": self.shared_records,
                "replicas": [r.doc() for r in self.replicas],
                "counters": self.fleet_counters(),
            }

    def _write_health(self) -> None:
        doc = self.health_doc()
        tmp = self.health_path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=2, default=str)
            os.replace(tmp, self.health_path)
        except OSError as e:
            print(f"WARNING: [fleet] health write failed: {e}")


def _pid_alive(pid: Optional[int]) -> bool:
    if not pid or pid < 0:
        return False
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    except OSError:
        return False
    return True


def install_fleet_signal_handlers(sup: FleetSupervisor) -> None:
    """SIGTERM/SIGINT -> drain the fleet; SIGHUP -> rolling restart
    (the operator's zero-downtime redeploy trigger)."""
    def _drain(signum, _frame):
        threading.Thread(
            target=lambda: (sup.drain_fleet(
                reason=signal.Signals(signum).name)),
            name="fleet-drain", daemon=True).start()

    def _roll(_signum, _frame):
        threading.Thread(target=sup.rolling_restart,
                         kwargs={"reason": "SIGHUP"},
                         name="fleet-rolling", daemon=True).start()

    signal.signal(signal.SIGTERM, _drain)
    signal.signal(signal.SIGINT, _drain)
    if hasattr(signal, "SIGHUP"):
        signal.signal(signal.SIGHUP, _roll)


def serve_fleet_forever(config: FleetConfig) -> int:
    """CLI runner: start, install signals, block until drained."""
    sup = FleetSupervisor(config)
    install_fleet_signal_handlers(sup)
    sup.start()
    ok = sup.wait_ready()
    print(f"[fleet] serving on {sup.endpoints_spec()} "
          f"(ready={ok}, replicas={len(sup.replicas)})", flush=True)
    sup._stopped.wait()
    # _stopped flips at the START of drain_fleet (stops the monitor);
    # exiting then would orphan still-draining replicas — block until
    # every replica has actually been drained or killed.
    per_rep = max(30.0, (config.query_timeout_s or 300.0) + 90.0)
    sup._drained.wait(timeout=per_rep * max(1, len(sup.replicas)))
    return 0
