"""The always-on query server: one shared Session behind a socket.

Execution model (docs/ARCHITECTURE.md "Serve layer"):

* one **accept thread** (``serve.accept`` fault probe) hands each
  connection a **reader thread** and an **executor thread**;
* a connection IS a stream: the reader admits requests (tenant budget
  -> bounded queue -> circuit breaker, ndstpu/serve/overload.py) and
  feeds them into the continuous-feed
  :class:`~ndstpu.harness.scheduler.StreamScheduler` — the SAME
  cross-stream compile-dedup machinery the batch throughput phase
  uses, so concurrent connections sending one plan shape share one
  compile;
* the executor drains its stream view through the
  :class:`~ndstpu.harness.admission.InprocAdmission` device gate, runs
  each query snapshot-pinned (``Session.pin_snapshot`` — results stay
  consistent under live ingest) under the PR 5 retry/quarantine
  contract, with the power watchdog idiom abandoning hung queries on
  a fresh session so neither the stream nor a drain ever wedges;
* the ``serve.dispatch`` fault probe sits BEFORE the retry wrapper:
  injected dispatch faults are client-visible typed errors, exercising
  the client's reconnect-and-retry path (serve_smoke leg 2).

Crash safety: every successful request journals its SQL + canonical
key (lifecycle.ServeJournal) and compile records persist incrementally
(``Session.compiled_count`` delta -> ``save_compiled``), so a SIGKILL
loses nothing a warm restart needs.  SIGTERM runs the graceful drain.

Fleet mode (serve/fleet.py) layers on top without changing the single
server: ``bind_early`` brings the listener(s) up before warmth so the
supervisor's ``probe`` verb can watch readiness flip, ``tcp`` adds a
TCP listener beside AF_UNIX (serve/transport.py), ``aot_corpus``
precompiles a full query corpus before readiness, ``replica_id`` tags
probe/health docs, and ``queue_depth=None`` derives admission depth
from the memplan device-memory model (``memplan.admission_budget``).
"""

from __future__ import annotations

import dataclasses
import os
import socket
import threading
import time
from typing import Dict, List, Optional

from ndstpu import faults, obs
from ndstpu.engine import columnar
from ndstpu.engine.session import Session
from ndstpu.engine.sql import ast, parse_statement
from ndstpu.harness import admission as adm
from ndstpu.harness import power
from ndstpu.harness.scheduler import StreamScheduler
from ndstpu.obs import ledger as ledger_mod
from ndstpu.serve import lifecycle, protocol, transport
from ndstpu.serve.overload import (AdmissionQueue, CircuitBreaker,
                                   Overloaded, Rejected, TenantBudgets)

# per-query watchdog (power idiom): a query hung past this is
# abandoned on a zombie thread and the server swaps to a fresh session
TIMEOUT_ENV = "NDSTPU_SERVE_QUERY_TIMEOUT_S"
DEFAULT_QUERY_TIMEOUT_S = 300.0


@dataclasses.dataclass
class ServeConfig:
    socket_path: str            # endpoint spec (unix path or tcp:H:P)
    input_prefix: Optional[str] = None
    engine: str = "cpu"
    output_prefix: Optional[str] = None
    output_format: str = "csv"
    compile_records: Optional[str] = None
    journal_path: Optional[str] = None
    slo_path: Optional[str] = None
    ledger_path: Optional[str] = None
    scale_factor: str = "unknown"
    floats: bool = False
    slots: int = 1
    queue_depth: Optional[int] = 64  # None/0 -> memplan admission model
    tenant_tokens: float = 64.0
    tenant_refill_per_s: float = 16.0
    breaker_cooldown_s: float = 5.0
    query_timeout_s: Optional[float] = None  # None -> env/default
    tcp: Optional[str] = None       # extra TCP listener (HOST:PORT)
    aot_corpus: Optional[str] = None  # stream file/dir to precompile
    bind_early: bool = False        # answer probes while warming
    replica_id: Optional[str] = None  # fleet identity in probe/health

    def resolved_timeout_s(self) -> float:
        if self.query_timeout_s is not None:
            return self.query_timeout_s
        try:
            return float(os.environ.get(
                TIMEOUT_ENV, DEFAULT_QUERY_TIMEOUT_S))
        except ValueError:
            return DEFAULT_QUERY_TIMEOUT_S


class _Conn:
    """One client connection = one scheduler stream."""

    def __init__(self, sid: str, sock: socket.socket):
        self.sid = sid
        self.sock = sock
        self.wlock = threading.Lock()
        self.pending: Dict[str, dict] = {}
        self.plock = threading.Lock()
        self.reader: Optional[threading.Thread] = None
        self.executor: Optional[threading.Thread] = None

    def send(self, obj: dict) -> None:
        with self.wlock:
            protocol.send_msg(self.sock, obj)


class QueryServer:
    """Front door + robustness control plane over one shared Session."""

    def __init__(self, config: ServeConfig,
                 session: Optional[Session] = None):
        self.config = config
        self.session = session
        self._session_lock = threading.Lock()
        self.ready = False
        self.draining = False
        self._drain_lock = threading.Lock()
        self._stopped = threading.Event()
        self._listeners: List[socket.socket] = []
        self.endpoints: List[transport.Endpoint] = []
        self._accept_threads: List[threading.Thread] = []
        self._conns: Dict[str, _Conn] = {}
        self._conns_lock = threading.Lock()
        self._conn_seq = 0
        self._req_seq = 0
        self._started_at = time.time()
        self._saved_compiled = 0
        self._zombies: List[dict] = []
        self.drain_summary: Optional[dict] = None
        self.aot_info: Optional[dict] = None

        self.retry_policy = faults.RetryPolicy.from_env()
        self.quarantine = faults.Quarantine()
        self.budgets = TenantBudgets(
            capacity=config.tenant_tokens,
            refill_per_s=config.tenant_refill_per_s)
        # queue_depth None/0 asks the memplan device-memory model how
        # many concurrently-admitted queries the budget supports — a
        # clamped NDSTPU_HBM_BYTES sheds instead of queueing
        self.admission_model: Optional[dict] = None
        depth = config.queue_depth
        if not depth:
            from ndstpu.engine import memplan
            self.admission_model = memplan.admission_budget()
            depth = self.admission_model["depth"]
        self.queue = AdmissionQueue(depth=depth)
        self.breaker = CircuitBreaker(
            self.quarantine, cooldown_s=config.breaker_cooldown_s)
        self.slo = lifecycle.SLOTracker()
        self.journal = lifecycle.ServeJournal(
            config.journal_path or "serve_journal.jsonl")
        self.gate = adm.InprocAdmission(config.slots)
        # built here (not in start) so bind_early connections accepted
        # while the session still warms get their stream immediately
        self.scheduler: StreamScheduler = StreamScheduler(
            {}, key_fn=lambda sql: self.session.canonical_key(sql))
        self.ledger: Optional[ledger_mod.Ledger] = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        """Build the session, warm-restart from the journal, precompile
        the AOT corpus, bind the socket, THEN flip readiness — a client
        that sees ready=True is guaranteed the replayed + precompiled
        warmth is already in place.  With ``bind_early`` the listener
        comes up first instead, answering probes (not-ready) and
        shedding sql as retryable ``overloaded`` while warming — the
        fleet supervisor's readiness gate."""
        if self.config.bind_early:
            self._bind()
            self._start_accepting()
        if self.session is None:
            from ndstpu.io import loader
            if not self.config.input_prefix:
                raise ValueError("ServeConfig needs input_prefix "
                                 "(or pass a prebuilt session)")
            with obs.span("load_catalog", cat="phase"):
                catalog = loader.load_catalog(
                    self.config.input_prefix,
                    use_decimal=not self.config.floats)
                self.session = Session(catalog,
                                       backend=self.config.engine)
        restart = lifecycle.warm_restart(
            self.session, self.journal,
            compile_records=self.config.compile_records
            if self._accel() else None)
        self._aot_precompile()
        self._saved_compiled = self.session.compiled_count()
        if self.config.ledger_path and \
                self.config.ledger_path.lower() != "none":
            try:
                self.ledger = ledger_mod.Ledger(self.config.ledger_path)
            except Exception as e:  # noqa: BLE001 — priors only
                print(f"WARNING: serve ledger not loaded: {e}")
        self.journal.mark_start({
            "engine": self.config.engine,
            "warm": restart,
            "aot": self.aot_info,
            "pid": os.getpid()})
        if not self._listeners:
            self._bind()
        self.ready = True
        self._start_accepting()
        obs.inc("serve.started")
        print(f"[serve] ready on "
              f"{','.join(ep.spec for ep in self.endpoints)} "
              f"(engine={self.config.engine}, slots={self.config.slots},"
              f" depth={self.queue.depth}, warm={restart})")

    def _accel(self) -> bool:
        return self.config.engine in ("tpu", "tpu-spmd")

    def _bind(self) -> None:
        specs = [self.config.socket_path]
        if self.config.tcp:
            tcp = str(self.config.tcp)
            specs.append(tcp if tcp.startswith("tcp:") else f"tcp:{tcp}")
        for ep in transport.parse_endpoints(specs):
            ls = transport.listen(ep)
            self._listeners.append(ls)
            self.endpoints.append(transport.bound_endpoint(ls))

    def _start_accepting(self) -> None:
        if self._accept_threads:
            return  # bind_early already started them
        for i, ls in enumerate(self._listeners):
            th = threading.Thread(
                target=self._accept_loop, args=(ls,),
                name=f"serve-accept-{i}", daemon=True)
            self._accept_threads.append(th)
            th.start()

    def _aot_precompile(self) -> None:
        """Full-corpus AOT warmth before readiness: plan every query in
        the configured stream file(s) (``canonical_key`` registers the
        fingerprint + plan cache without executing), so combined with
        preloaded compile records a replica's first seen-shape query
        compiles nothing.  Defects degrade to cold queries, never a
        failed boot."""
        corpus = self.config.aot_corpus
        if not corpus:
            return
        t0 = time.time()
        import glob as _glob
        if os.path.isdir(corpus):
            files = sorted(_glob.glob(os.path.join(corpus, "query_*.sql")))
        else:
            files = [corpus]
        planned = errors = 0
        for path in files:
            try:
                queries = power.gen_sql_from_stream(path)
            except Exception as e:  # noqa: BLE001
                print(f"WARNING: aot corpus {path} unreadable: {e}")
                errors += 1
                continue
            for name, sql in queries.items():
                try:
                    self.session.canonical_key(sql)
                    planned += 1
                except Exception as e:  # noqa: BLE001
                    errors += 1
                    print(f"WARNING: aot precompile skipped {name}: {e}")
        self.aot_info = {"files": len(files), "planned": planned,
                         "errors": errors,
                         "wall_s": round(time.time() - t0, 3)}
        obs.inc("serve.aot.planned", planned)
        print(f"[serve] aot precompile: {self.aot_info}")

    def serve_forever(self) -> None:
        self.start()
        self._stopped.wait()

    def drain(self, reason: str = "drain") -> dict:
        """Graceful shutdown: stop admission, finish in-flight work,
        flush artifacts, journal the clean marker.  Idempotent; a hung
        in-flight query is abandoned by the watchdog, so this returns
        within ~query_timeout even under a wedged engine."""
        with self._drain_lock:
            if self.draining:
                self._stopped.wait()
                return self.drain_summary or {}
            self.draining = True
        obs.inc("serve.drain.initiated")
        print(f"[serve] draining ({reason}): admission stopped, "
              f"finishing in-flight queries")
        for ls in self._listeners:
            try:
                ls.close()
            except OSError:
                pass
        with self._conns_lock:
            conns = list(self._conns.values())
        for conn in conns:
            self.scheduler.close(conn.sid)
        timeout = self.config.resolved_timeout_s() + 30.0
        for conn in conns:
            th = conn.executor
            if th is not None and th is not threading.current_thread():
                th.join(timeout)
        inflight_done = obs.counters_snapshot().get("serve.ok", 0)
        self._flush(reason)
        for conn in conns:
            try:
                conn.sock.close()
            except OSError:
                pass
        self.ready = False
        self.drain_summary = {
            "reason": reason,
            "ok_total": inflight_done,
            "connections": len(conns),
        }
        obs.inc("serve.drain.completed")
        print(f"[serve] drain complete: {self.drain_summary}")
        self._stopped.set()
        return self.drain_summary

    def _flush(self, reason: str) -> None:
        """Persist everything a restart (or postmortem) needs."""
        self._persist_compiled(force=True)
        if self.config.slo_path:
            try:
                self.slo.export(self.config.slo_path)
            except Exception as e:  # noqa: BLE001
                print(f"WARNING: SLO export failed: {e}")
        self.journal.mark_clean_shutdown({"reason": reason})

    # -- accept / per-connection threads -------------------------------------

    def _accept_loop(self, listener: socket.socket) -> None:
        while not self.draining:
            try:
                sock, _addr = listener.accept()
            except OSError:
                break  # listener closed by drain
            transport.configure(sock)  # per-connection read timeout
            try:
                faults.check("serve.accept")
            except Exception as e:  # noqa: BLE001 — injected fault:
                # drop the connection; the client's reconnect path is
                # exactly what this probe exists to exercise
                obs.inc("serve.accept.faulted")
                print(f"[serve] accept fault, dropping connection: {e}")
                try:
                    sock.close()
                except OSError:
                    pass
                continue
            with self._conns_lock:
                self._conn_seq += 1
                sid = f"conn{self._conn_seq}"
                conn = _Conn(sid, sock)
                self._conns[sid] = conn
            obs.inc("serve.accepted")
            self.scheduler.open_stream(sid)
            conn.reader = threading.Thread(
                target=self._reader_loop, args=(conn,),
                name=f"serve-read-{sid}", daemon=True)
            conn.executor = threading.Thread(
                target=self._executor_loop, args=(conn,),
                name=f"serve-exec-{sid}", daemon=True)
            conn.reader.start()
            conn.executor.start()

    def _reader_loop(self, conn: _Conn) -> None:
        try:
            while True:
                try:
                    msg = protocol.recv_msg(conn.sock)
                except (protocol.ProtocolError, OSError) as e:
                    if not self.draining:
                        print(f"[serve] {conn.sid} read error: {e}")
                    break
                if msg is None:
                    break  # clean hangup
                try:
                    self._handle(conn, msg)
                except OSError:
                    break  # peer gone mid-reply
        finally:
            self.scheduler.close(conn.sid)
            obs.inc("serve.connections.closed")

    def _handle(self, conn: _Conn, msg: dict) -> None:
        op = msg.get("op")
        rid = str(msg.get("id") or f"r{self._next_req()}")
        if op == "ping":
            conn.send({"status": "ok", "id": rid, "pong": True})
        elif op == "ready":
            conn.send({"status": "ok", "id": rid,
                       "ready": self.ready and not self.draining})
        elif op == "health":
            conn.send({"status": "ok", "id": rid,
                       "health": self.health()})
        elif op == "probe":
            conn.send({"status": "ok", "id": rid,
                       "probe": self.probe_doc()})
        elif op == "stats":
            conn.send({"status": "ok", "id": rid,
                       "counters": obs.counters_snapshot(),
                       "slo": self.slo.snapshot()})
        elif op == "drain":
            conn.send({"status": "ok", "id": rid, "draining": True})
            threading.Thread(target=self.drain,
                             kwargs={"reason": "client-request"},
                             name="serve-drain", daemon=True).start()
        elif op == "sql":
            self._admit_sql(conn, rid, msg)
        else:
            conn.send({"status": "error", "id": rid,
                       "error": f"unknown op {op!r}",
                       "taxonomy": "permanent"})

    def _next_req(self) -> int:
        with self._conns_lock:
            self._req_seq += 1
            return self._req_seq

    def _admit_sql(self, conn: _Conn, rid: str, msg: dict) -> None:
        """Reader-side admission: typed shedding BEFORE any engine
        work, so an overloaded server answers in O(socket write)."""
        tenant = str(msg.get("tenant") or "default")
        sql = msg.get("sql")
        obs.inc("serve.requests")
        if not sql or not isinstance(sql, str):
            conn.send({"status": "error", "id": rid,
                       "error": "sql op needs a 'sql' string",
                       "taxonomy": "permanent"})
            return
        if self.draining:
            obs.inc("serve.draining_rejects")
            conn.send({"status": "draining", "id": rid,
                       "error": "server is draining"})
            return
        if not self.ready:
            # bind_early boot: the listener answers before the session
            # is warm.  Retryable overload (NOT draining) so a fleet
            # client's retry lands on a ready sibling and a lone
            # client just backs off until readiness flips.
            obs.inc("serve.warming_rejects")
            conn.send({"status": "overloaded", "id": rid,
                       "error": "server warming up (not ready)",
                       "retry_after_s": 0.25})
            return
        try:
            self.budgets.acquire(tenant)
            self.queue.admit(deadline_s=msg.get("deadline_s"))
        except Overloaded as e:
            obs.inc("serve.overloaded")
            self.slo.record(tenant, 0.0, "overloaded")
            conn.send({"status": "overloaded", "id": rid,
                       "error": str(e),
                       "retry_after_s": e.retry_after_s})
            return
        except Rejected as e:
            obs.inc("serve.rejected")
            obs.inc(f"serve.rejected.{e.reason}")
            self.slo.record(tenant, 0.0, "rejected")
            conn.send({"status": "rejected", "id": rid,
                       "error": str(e), "reason": e.reason})
            return
        # canonical key drives BOTH compile dedup and the breaker /
        # quarantine identity: a tripped plan SHAPE fast-fails every
        # rendering of it, whatever the literals
        canon = self.session.canonical_key(sql)
        try:
            self.breaker.check(canon)
        except Rejected as e:
            self.queue.release()
            obs.inc("serve.rejected")
            obs.inc("serve.rejected.circuit-open")
            self.slo.record(tenant, 0.0, "rejected")
            conn.send({"status": "rejected", "id": rid,
                       "error": str(e), "reason": e.reason})
            return
        req = {"id": rid, "sql": sql, "tenant": tenant,
               "name": msg.get("name"), "canon": canon,
               "max_rows": msg.get("max_rows", 100),
               "admitted_at": time.time()}
        with conn.plock:
            conn.pending[rid] = req
        try:
            self.scheduler.feed(conn.sid, rid, sql)
        except ValueError:  # stream closed by a racing drain
            with conn.plock:
                conn.pending.pop(rid, None)
            self.queue.release()
            obs.inc("serve.draining_rejects")
            conn.send({"status": "draining", "id": rid,
                       "error": "server is draining"})

    # -- executor ------------------------------------------------------------

    def _executor_loop(self, conn: _Conn) -> None:
        t0 = time.time()
        view = self.scheduler.view(conn.sid)
        while True:
            rid = view.next(time.time() - t0)
            if rid is None:
                break
            with conn.plock:
                req = conn.pending.get(rid)
            if req is None:
                view.done(rid, failed=True)
                continue
            failed = self._dispatch(conn, req)
            view.done(rid, failed=failed)
            with conn.plock:
                conn.pending.pop(rid, None)

    def _dispatch(self, conn: _Conn, req: dict) -> bool:
        """Run one admitted request end to end; returns failed?"""
        rid, tenant, canon = req["id"], req["tenant"], req["canon"]
        name = req.get("name") or rid
        qspan = obs.span(name, cat="query", collect=True,
                         tenant=tenant, serve=1)
        t0 = time.time()
        try:
            # chaos-only: an injected replica crash takes the WHOLE
            # process down mid-flight (fleet_smoke scenario 2 without
            # needing an external SIGKILL) — the supervisor restarts
            # us, the client fails over to a sibling
            faults.check("serve.replica.crash", key=name)
        except faults.InjectedFault:
            obs.inc("serve.replica.crashed")
            print(f"[serve] injected replica crash on {name}; exiting",
                  flush=True)
            os._exit(17)
        try:
            # pre-retry, client-visible: an injected dispatch fault
            # reaches the client as a typed transient error and the
            # CLIENT retries (serve_smoke leg 2)
            faults.check("serve.dispatch", key=name)
            with qspan:
                result, attempts = faults.run_with_retry(
                    lambda: self._run_guarded(req),
                    key=canon, policy=self.retry_policy,
                    quarantine=self.quarantine)
        except Exception as e:  # noqa: BLE001 — classified reply
            from ndstpu.faults import taxonomy
            klass = getattr(e, "taxonomy", None) or taxonomy.classify(e)
            wall = time.time() - t0
            obs.inc("serve.errors")
            if self.breaker.note_failure(canon):
                obs.inc("serve.breaker.tripped")
                print(f"[serve] circuit tripped for plan shape "
                      f"{canon[:48]!r}")
            self.slo.record(tenant, wall, "error")
            try:
                conn.send({"status": "error", "id": rid,
                           "error": str(e),
                           "type": type(e).__name__,
                           "taxonomy": klass,
                           "attempts": getattr(e, "attempts", 1)})
            except OSError:
                pass
            return True
        finally:
            self.queue.release()
        wall = qspan.wall_s or (time.time() - t0)
        obs.inc("serve.ok")
        self.breaker.note_success(canon)
        self.queue.observe(wall)  # EWMA behind retry_after_s hints
        self.slo.record(tenant, wall, "ok")
        self.journal.mark_query(name, req["sql"], canon_key=canon)
        self._persist_compiled()
        self._ledger_append(name, tenant, qspan)
        resp = {"status": "ok", "id": rid,
                "wall_s": round(wall, 6), "attempts": attempts}
        resp.update(result)
        try:
            conn.send(resp)
        except OSError:
            pass  # client gone; work is journaled regardless
        return False

    def _run_guarded(self, req: dict) -> dict:
        """One attempt, under the device gate + watchdog."""
        timeout = self.config.resolved_timeout_s()
        with self.gate.slot():
            if timeout <= 0:
                return self._run_query(self.session, req)
            slot: dict = {}
            with self._session_lock:
                sess = self.session

            def work():
                try:
                    slot["result"] = self._run_query(sess, req)
                except Exception as e:  # noqa: BLE001
                    slot["err"] = e

            th = threading.Thread(target=work, daemon=True,
                                  name=f"serve-q-{req['id']}")
            th.start()
            th.join(timeout)
            if th.is_alive():
                # power watchdog idiom: abandon the wedged thread and
                # swap every future request onto a fresh session — the
                # drain path depends on this never blocking forever
                self._zombies.append({"th": th, "name": req["id"]})
                obs.inc("serve.watchdog.abandoned")
                self._swap_session(sess)
                raise TimeoutError(
                    f"{req['id']} hung > {timeout:.0f}s; abandoned "
                    f"(server continues on a fresh session)")
            if "err" in slot:
                raise slot["err"]
            return slot["result"]

    def _swap_session(self, old: Session) -> None:
        with self._session_lock:
            if self.session is not old:
                return  # another watchdog already swapped
            try:
                fresh = Session(old.catalog, backend=old.backend,
                                views=dict(old.views),
                                warehouse=old.warehouse)
                fresh.spmd_threshold = old.spmd_threshold
                fresh.spmd_chunk_rows = old.spmd_chunk_rows
                fresh.spmd_prefetch_depth = old.spmd_prefetch_depth
                self.session = fresh
                if self.config.compile_records and self._accel():
                    fresh.preload_compiled(self.config.compile_records)
            except Exception as e:  # noqa: BLE001
                print(f"WARNING: fresh session setup after hang "
                      f"incomplete: {e}")

    def _run_query(self, session: Session, req: dict) -> dict:
        """Execute snapshot-pinned; write or collect the result."""
        sql = req["sql"]
        pin = None
        try:
            if isinstance(parse_statement(sql), ast.Query):
                pin = session.pin_snapshot()
        except Exception:  # noqa: BLE001 — let sql() raise properly
            pass
        result = session.sql(sql, pin=pin)
        if result is None:
            return {"rows": 0, "ddl": True}
        name = req.get("name")
        if name and self.config.output_prefix:
            safe = os.path.normpath(str(name))
            if safe.startswith(("..", "/")):
                raise ValueError(f"bad output name {name!r}")
            out = power.ensure_valid_column_names(result)
            dest = os.path.join(self.config.output_prefix, safe)
            os.makedirs(dest, exist_ok=True)
            at = columnar.to_arrow(out)
            if self.config.output_format == "parquet":
                import pyarrow.parquet as pq
                pq.write_table(at, os.path.join(dest, "part-0.parquet"))
            elif self.config.output_format == "csv":
                import pyarrow.csv as pacsv
                pacsv.write_csv(at, os.path.join(dest, "part-0.csv"))
            else:
                raise ValueError(f"unsupported output format "
                                 f"{self.config.output_format}")
            return {"rows": result.num_rows, "output": safe}
        rows = result.to_rows()
        cap = int(req.get("max_rows") or 100)
        return {"rows": len(rows),
                "columns": list(result.columns),
                "data": [list(r) for r in rows[:cap]],
                "truncated": len(rows) > cap}

    # -- persistence / health ------------------------------------------------

    def _persist_compiled(self, force: bool = False) -> None:
        """Incremental compile-record persistence: a SIGKILL'd server
        must warm-restart from everything compiled before the kill, so
        records save after every compile-growing request, not just on
        clean drain."""
        if not (self.config.compile_records and self._accel()):
            return
        n = self.session.compiled_count()
        if not force and n <= self._saved_compiled:
            return
        try:
            self.session.save_compiled(self.config.compile_records)
            self._saved_compiled = n
        except Exception as e:  # noqa: BLE001
            print(f"WARNING: compile records not saved: {e}")

    def _ledger_append(self, name: str, tenant: str, qspan) -> None:
        if self.ledger is None:
            return
        try:
            b = qspan.buckets or {}
            self.ledger.append([ledger_mod.make_entry(
                name, qspan.wall_s, b.get("compile_s", 0.0),
                b.get("execute_s", 0.0), engine=self.config.engine,
                scale_factor=self.config.scale_factor, seed="serve",
                source="serve",
                extra={"tenant": tenant, "mode": "serve"})])
        except Exception as e:  # noqa: BLE001 — ledger never fails a
            print(f"WARNING: serve ledger append failed: {e}")  # query

    def probe_doc(self) -> dict:
        """The fleet supervisor's liveness/readiness view.  Cheap —
        answered even while a ``bind_early`` boot is still warming."""
        return {
            "alive": True,
            "ready": self.ready and not self.draining,
            "draining": self.draining,
            "pid": os.getpid(),
            "replica_id": self.config.replica_id,
            "endpoints": [ep.spec for ep in self.endpoints],
            "started_at": self._started_at,
            "uptime_s": round(time.time() - self._started_at, 3),
            "aot": self.aot_info,
            "queue": self.queue.snapshot(),
        }

    def health(self) -> dict:
        c = obs.counters_snapshot()
        return {
            "alive": True,
            "ready": self.ready and not self.draining,
            "draining": self.draining,
            "uptime_s": round(time.time() - self._started_at, 3),
            "engine": self.config.engine,
            "replica_id": self.config.replica_id,
            "endpoints": [ep.spec for ep in self.endpoints],
            "connections": len(self._conns),
            "admitted": self.queue.admitted,
            "admitted_peak": self.queue.peak,
            "queue_depth": self.queue.depth,
            "est_wait_s": round(self.queue.est_wait_s, 6),
            "admission_model": self.admission_model,
            "compiled": self.session.compiled_count()
            if self.session is not None else 0,
            "zombies": sum(1 for z in self._zombies
                           if z["th"].is_alive()),
            "requests": c.get("serve.requests", 0),
            "ok": c.get("serve.ok", 0),
            "errors": c.get("serve.errors", 0),
            "overloaded": c.get("serve.overloaded", 0),
            "rejected": c.get("serve.rejected", 0),
        }
