"""Reconnect-and-retry client for the query server.

The retry policy mirrors the server's typed shedding contract
(serve/protocol.py):

* **connection faults** (refused / reset / broken pipe / injected
  ``serve.accept`` drops) — reconnect with deterministic backoff; all
  classified transient by ndstpu/faults/taxonomy.py;
* **``overloaded``** — sleep the server's ``retry_after_s`` hint, then
  resend;
* **``error`` with ``taxonomy: transient``** (injected
  ``serve.dispatch`` faults, watchdog abandonment) — resend;
* **``rejected``** / **``error`` permanent** — raise immediately:
  the server said retrying unchanged cannot help;
* **``draining``** — raise :class:`ServerDraining` (transient kind):
  callers that know a restart is coming (chaos scenario H) keep
  retrying until the new incarnation answers.
"""

from __future__ import annotations

import socket
import time
from typing import Optional

from ndstpu.serve import protocol
from ndstpu.serve.overload import Rejected


class ServeError(RuntimeError):
    """A permanent server-side failure, taxonomy attached."""

    def __init__(self, message: str, taxonomy: str = "permanent",
                 response: Optional[dict] = None):
        super().__init__(message)
        self.taxonomy = taxonomy
        self.kind = taxonomy  # faults.taxonomy.classify reads .kind
        self.response = response or {}


class ServerDraining(RuntimeError):
    kind = "transient"


class ServeClient:
    """One logical client; transparently reconnects across retries."""

    def __init__(self, socket_path: str, tenant: str = "default",
                 retries: int = 8, backoff_s: float = 0.05,
                 max_backoff_s: float = 2.0,
                 connect_timeout_s: float = 30.0):
        self.socket_path = socket_path
        self.tenant = tenant
        self.retries = retries
        self.backoff_s = backoff_s
        self.max_backoff_s = max_backoff_s
        self.connect_timeout_s = connect_timeout_s
        self._sock: Optional[socket.socket] = None
        self._seq = 0
        self.retried = 0  # observable: how often retry paths fired

    # -- transport -----------------------------------------------------------

    def _connect(self) -> socket.socket:
        if self._sock is not None:
            return self._sock
        deadline = time.monotonic() + self.connect_timeout_s
        wait = self.backoff_s
        while True:
            s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            try:
                s.connect(self.socket_path)
                self._sock = s
                return s
            except OSError:
                s.close()
                if time.monotonic() >= deadline:
                    raise
                time.sleep(wait)
                wait = min(wait * 2, self.max_backoff_s)

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _drop(self) -> None:
        self.close()

    def _roundtrip(self, msg: dict) -> dict:
        sock = self._connect()
        protocol.send_msg(sock, msg)
        resp = protocol.recv_msg(sock)
        if resp is None:
            raise ConnectionResetError("server closed the connection")
        return resp

    # -- request with the typed retry contract -------------------------------

    def request(self, msg: dict) -> dict:
        attempt = 0
        wait = self.backoff_s
        while True:
            attempt += 1
            try:
                resp = self._roundtrip(msg)
            except (OSError, protocol.ProtocolError):
                self._drop()
                if attempt > self.retries:
                    raise
                self.retried += 1
                time.sleep(wait)
                wait = min(wait * 2, self.max_backoff_s)
                continue
            status = resp.get("status")
            if status == "ok":
                return resp
            if status == "overloaded":
                if attempt > self.retries:
                    raise ServeError(
                        f"still overloaded after {attempt} attempts: "
                        f"{resp.get('error')}", taxonomy="transient",
                        response=resp)
                self.retried += 1
                time.sleep(float(resp.get("retry_after_s") or wait))
                continue
            if status == "draining":
                raise ServerDraining(
                    resp.get("error") or "server is draining")
            if status == "rejected":
                raise Rejected(resp.get("error") or "rejected",
                               reason=resp.get("reason") or "rejected")
            # status == "error": retry transient, raise permanent
            taxonomy = resp.get("taxonomy") or "permanent"
            if taxonomy == "transient" and attempt <= self.retries:
                self.retried += 1
                time.sleep(wait)
                wait = min(wait * 2, self.max_backoff_s)
                continue
            raise ServeError(
                f"{resp.get('type', 'Error')}: {resp.get('error')}",
                taxonomy=taxonomy, response=resp)

    # -- ops -----------------------------------------------------------------

    def _rid(self) -> str:
        self._seq += 1
        return f"{self.tenant}-{self._seq}"

    def sql(self, sql: str, name: Optional[str] = None,
            deadline_s: Optional[float] = None,
            tenant: Optional[str] = None,
            max_rows: int = 100) -> dict:
        msg = {"op": "sql", "id": self._rid(), "sql": sql,
               "tenant": tenant or self.tenant, "max_rows": max_rows}
        if name is not None:
            msg["name"] = name
        if deadline_s is not None:
            msg["deadline_s"] = deadline_s
        return self.request(msg)

    def ping(self) -> dict:
        return self.request({"op": "ping", "id": self._rid()})

    def health(self) -> dict:
        return self.request(
            {"op": "health", "id": self._rid()})["health"]

    def stats(self) -> dict:
        return self.request({"op": "stats", "id": self._rid()})

    def drain(self) -> dict:
        return self.request({"op": "drain", "id": self._rid()})

    def wait_ready(self, timeout_s: float = 120.0,
                   poll_s: float = 0.1) -> bool:
        """Poll readiness (warm restart flips it only after replay)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            try:
                resp = self._roundtrip(
                    {"op": "ready", "id": self._rid()})
                if resp.get("ready"):
                    return True
            except (OSError, protocol.ProtocolError):
                self._drop()
            time.sleep(poll_s)
        return False
