"""Reconnect-and-retry client for the query server / serving fleet.

The retry policy mirrors the server's typed shedding contract
(serve/protocol.py):

* **connection faults** (refused / reset / broken pipe / injected
  ``serve.accept`` drops) — reconnect with deterministic backoff; all
  classified transient by ndstpu/faults/taxonomy.py;
* **``overloaded``** — sleep the server's ``retry_after_s`` hint, then
  resend;
* **``error`` with ``taxonomy: transient``** (injected
  ``serve.dispatch`` faults, watchdog abandonment) — resend;
* **``rejected``** / **``error`` permanent** — raise immediately:
  the server said retrying unchanged cannot help;
* **``draining``** — raise :class:`ServerDraining` (transient kind):
  callers that know a restart is coming (chaos scenario H) keep
  retrying until the new incarnation answers.

**Multi-endpoint failover (fleet mode).**  ``ServeClient`` accepts a
comma-separated endpoint list (serve/transport.py grammar: AF_UNIX
paths and/or ``tcp:HOST:PORT``).  With more than one endpoint the
contract extends — every switch increments the ``failovers`` evidence
counter:

* a connection fault (``ConnectionRefusedError``/reset — a crashed or
  restarting replica) rotates to the next endpoint and re-submits the
  idempotent read there;
* ``overloaded``/``draining`` from one replica rotates too, so a shed
  request lands on a sibling instead of queueing behind the loaded or
  restarting one;
* only when **every** endpoint refuses for the whole connect window
  does the client raise :class:`NoHealthyEndpoint` (transient, lists
  the endpoints tried).

With a single endpoint the PR 14 behavior is unchanged byte for byte.
"""

from __future__ import annotations

import socket
import time
import zlib
from typing import List, Optional

from ndstpu.serve import protocol, transport
from ndstpu.serve.overload import Rejected


class ServeError(RuntimeError):
    """A permanent server-side failure, taxonomy attached."""

    def __init__(self, message: str, taxonomy: str = "permanent",
                 response: Optional[dict] = None):
        super().__init__(message)
        self.taxonomy = taxonomy
        self.kind = taxonomy  # faults.taxonomy.classify reads .kind
        self.response = response or {}


class ServerDraining(RuntimeError):
    kind = "transient"


class NoHealthyEndpoint(ConnectionError):
    """Every fleet endpoint refused for the whole connect window.

    Subclasses :class:`ConnectionError`, so faults/taxonomy.py
    classifies it transient — an outer retry loop may find the fleet
    back up."""

    kind = "transient"

    def __init__(self, endpoints: List[str], last_error: str):
        super().__init__(
            f"no healthy endpoint among {len(endpoints)}: "
            f"{', '.join(endpoints)} (last error: {last_error})")
        self.endpoints = list(endpoints)
        self.last_error = last_error


class ServeClient:
    """One logical client; transparently reconnects across retries and
    rotates across fleet endpoints on connection faults and sheds."""

    def __init__(self, endpoints, tenant: str = "default",
                 retries: int = 8, backoff_s: float = 0.05,
                 max_backoff_s: float = 2.0,
                 connect_timeout_s: float = 30.0):
        self.endpoints = transport.parse_endpoints(endpoints)
        if not self.endpoints:
            raise ValueError("ServeClient needs at least one endpoint")
        # single-endpoint compat: existing callers read .socket_path
        self.socket_path = (self.endpoints[0].path
                            if self.endpoints[0].kind == "unix"
                            else self.endpoints[0].spec)
        self.tenant = tenant
        self.retries = retries
        self.backoff_s = backoff_s
        self.max_backoff_s = max_backoff_s
        self.connect_timeout_s = connect_timeout_s
        self._sock: Optional[socket.socket] = None
        # initial endpoint spread: stable per tenant, so a fleet of
        # clients distributes across replicas instead of all piling
        # onto endpoints[0] (failover still sweeps the full list)
        self._idx = (zlib.crc32(tenant.encode()) % len(self.endpoints)
                     if len(self.endpoints) > 1 else 0)
        self._seq = 0
        self.retried = 0    # observable: how often retry paths fired
        self.failovers = 0  # observable: endpoint switches (fleet)

    # -- transport -----------------------------------------------------------

    @property
    def endpoint(self) -> transport.Endpoint:
        """The endpoint the client currently prefers / is attached to."""
        return self.endpoints[self._idx % len(self.endpoints)]

    def _connect(self) -> socket.socket:
        """Attach to the preferred endpoint, sweeping the rest of the
        fleet on refusal; bounded by ``connect_timeout_s`` overall."""
        if self._sock is not None:
            return self._sock
        deadline = time.monotonic() + self.connect_timeout_s
        wait = self.backoff_s
        n = len(self.endpoints)
        last_err: Optional[OSError] = None
        while True:
            for hop in range(n):
                ep = self.endpoints[(self._idx + hop) % n]
                try:
                    s = transport.connect(ep)
                except OSError as exc:
                    last_err = exc
                    continue
                if hop and n > 1:
                    self.failovers += 1
                self._idx = (self._idx + hop) % n
                self._sock = s
                return s
            if time.monotonic() >= deadline:
                if n > 1:
                    raise NoHealthyEndpoint(
                        [ep.spec for ep in self.endpoints],
                        last_error=str(last_err)) from last_err
                raise last_err  # single-endpoint: PR 14 behavior
            time.sleep(wait)
            wait = min(wait * 2, self.max_backoff_s)

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _drop(self) -> None:
        self.close()

    def _failover(self) -> None:
        """Abandon the current endpoint: next attempt starts the sweep
        at its sibling.  No-op beyond dropping with one endpoint."""
        self._drop()
        if len(self.endpoints) > 1:
            self._idx = (self._idx + 1) % len(self.endpoints)
            self.failovers += 1

    def _roundtrip(self, msg: dict) -> dict:
        sock = self._connect()
        protocol.send_msg(sock, msg)
        resp = protocol.recv_msg(sock)
        if resp is None:
            raise ConnectionResetError("server closed the connection")
        return resp

    # -- request with the typed retry contract -------------------------------

    def request(self, msg: dict) -> dict:
        attempt = 0
        wait = self.backoff_s
        fleet = len(self.endpoints) > 1
        while True:
            attempt += 1
            try:
                resp = self._roundtrip(msg)
            except (OSError, protocol.ProtocolError):
                self._failover()
                if attempt > self.retries:
                    raise
                self.retried += 1
                time.sleep(wait)
                wait = min(wait * 2, self.max_backoff_s)
                continue
            status = resp.get("status")
            if status == "ok":
                return resp
            if status == "overloaded":
                if attempt > self.retries:
                    raise ServeError(
                        f"still overloaded after {attempt} attempts: "
                        f"{resp.get('error')}", taxonomy="transient",
                        response=resp)
                self.retried += 1
                hint = float(resp.get("retry_after_s") or wait)
                if fleet:
                    # shed here should land on a sibling: rotate and
                    # retry promptly at first (another replica may be
                    # idle), then back off toward the service-time
                    # hint so the attempt budget spans real queries
                    # instead of exhausting in one fast sweep
                    self._failover()
                    time.sleep(min(max(hint, wait),
                                   self.max_backoff_s))
                    wait = min(wait * 2, self.max_backoff_s)
                else:
                    time.sleep(hint)
                continue
            if status == "draining":
                if fleet and attempt <= self.retries:
                    # rolling restart: the rest of the fleet serves
                    self._failover()
                    self.retried += 1
                    time.sleep(wait)
                    continue
                raise ServerDraining(
                    resp.get("error") or "server is draining")
            if status == "rejected":
                raise Rejected(resp.get("error") or "rejected",
                               reason=resp.get("reason") or "rejected")
            # status == "error": retry transient, raise permanent
            taxonomy = resp.get("taxonomy") or "permanent"
            if taxonomy == "transient" and attempt <= self.retries:
                self.retried += 1
                time.sleep(wait)
                wait = min(wait * 2, self.max_backoff_s)
                continue
            raise ServeError(
                f"{resp.get('type', 'Error')}: {resp.get('error')}",
                taxonomy=taxonomy, response=resp)

    # -- ops -----------------------------------------------------------------

    def _rid(self) -> str:
        self._seq += 1
        return f"{self.tenant}-{self._seq}"

    def sql(self, sql: str, name: Optional[str] = None,
            deadline_s: Optional[float] = None,
            tenant: Optional[str] = None,
            max_rows: int = 100) -> dict:
        msg = {"op": "sql", "id": self._rid(), "sql": sql,
               "tenant": tenant or self.tenant, "max_rows": max_rows}
        if name is not None:
            msg["name"] = name
        if deadline_s is not None:
            msg["deadline_s"] = deadline_s
        return self.request(msg)

    def ping(self) -> dict:
        return self.request({"op": "ping", "id": self._rid()})

    def health(self) -> dict:
        return self.request(
            {"op": "health", "id": self._rid()})["health"]

    def probe(self) -> dict:
        """Liveness/readiness probe (answered even before readiness)."""
        return self.request(
            {"op": "probe", "id": self._rid()})["probe"]

    def stats(self) -> dict:
        return self.request({"op": "stats", "id": self._rid()})

    def drain(self) -> dict:
        return self.request({"op": "drain", "id": self._rid()})

    def wait_ready(self, timeout_s: float = 120.0,
                   poll_s: float = 0.1) -> bool:
        """Poll readiness (warm restart + AOT precompile flip it only
        after they complete); any one ready endpoint suffices."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            try:
                resp = self._roundtrip(
                    {"op": "ready", "id": self._rid()})
                if resp.get("ready"):
                    return True
                if len(self.endpoints) > 1:
                    self._drop()  # not ready: try the next replica
                    self._idx = (self._idx + 1) % len(self.endpoints)
            except (OSError, protocol.ProtocolError):
                self._drop()
                if len(self.endpoints) > 1:
                    self._idx = (self._idx + 1) % len(self.endpoints)
            time.sleep(poll_s)
        return False
