"""Always-on query service: the serve layer over one shared Session.

The batch harness (power/throughput) runs fixed work lists and exits;
``ndstpu.serve`` keeps the engine resident and puts a fault-tolerant
front door on it:

* :mod:`ndstpu.serve.protocol` — length-prefixed JSON request framing
  shared by server and client;
* :mod:`ndstpu.serve.overload` — admission control: bounded queue,
  per-tenant token budgets, deadline-aware shedding, and a per-plan-
  shape circuit breaker over the PR 5 quarantine list;
* :mod:`ndstpu.serve.lifecycle` — the robustness control plane:
  append-only serve journal, SIGTERM graceful drain, crash-safe warm
  restart, and per-tenant latency SLO export (``SLO.json``);
* :mod:`ndstpu.serve.server` — the socket front door feeding the
  continuous-feed :class:`~ndstpu.harness.scheduler.StreamScheduler`
  and :class:`~ndstpu.harness.admission.InprocAdmission`;
* :mod:`ndstpu.serve.client` — reconnect-and-retry client.

Entry point: ``ndstpu-serve`` (ndstpu/harness/serve.py).  Gated by
``scripts/serve_smoke.py`` in CI (docs/ROBUSTNESS.md "Serving
lifecycle").
"""

from ndstpu.serve.overload import (  # noqa: F401
    AdmissionQueue,
    CircuitBreaker,
    Overloaded,
    Rejected,
    TenantBudgets,
)
