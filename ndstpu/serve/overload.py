"""Admission control for the query server: shed load, don't queue it.

Three guards run before a request touches the engine, each returning a
*typed* outcome instead of unbounded queuing (the reference delegates
this whole layer to Spark's scheduler backpressure):

* :class:`TenantBudgets` — per-tenant token buckets.  A tenant at
  budget gets :class:`Rejected` while other tenants proceed; tokens
  refill continuously so a backed-off client recovers on its own.
* :class:`AdmissionQueue` — a bounded count of admitted-but-unfinished
  requests.  At depth, new work gets :class:`Overloaded` (retriable,
  with a ``retry_after_s`` hint); a request whose ``deadline_s`` the
  projected queue wait already busts is shed as :class:`Rejected`
  ("deadline") — running it would waste device time on an answer the
  client will no longer accept.  The per-item wait estimate behind
  both hints is an **EWMA of observed service walls**
  (:meth:`AdmissionQueue.observe`), seeded by ``est_wait_s`` until the
  first completion — a slow corpus pushes clients off proportionally
  harder than a fast one, and the hint decays as the server speeds
  back up.  Depth itself can come from the memplan device-memory
  model (``engine/memplan.py:admission_budget``) instead of the
  static 64 when the server is configured with ``queue_depth=auto``.
* :class:`CircuitBreaker` — per canonical plan fingerprint, tripped by
  the PR 5 :class:`~ndstpu.faults.Quarantine` poison list: once a plan
  shape is quarantined the breaker fast-fails further requests for it
  (:class:`Rejected`, "circuit-open") instead of burning retries, and
  recovers via a half-open probe after ``cooldown_s``.

All guards take an injectable monotonic ``clock`` so the cooldown /
refill edges are unit-testable without sleeping.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional


class Overloaded(Exception):
    """Server momentarily full — retriable after ``retry_after_s``."""

    # taxonomy hook (faults/taxonomy.py reads .kind first): a client
    # retry loop treats overload like any transient fault
    kind = "transient"

    def __init__(self, message: str, retry_after_s: float = 0.1):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class Rejected(Exception):
    """Typed refusal (budget / deadline / circuit) — retrying the same
    request unchanged cannot help, so clients must not."""

    kind = "permanent"

    def __init__(self, message: str, reason: str):
        super().__init__(message)
        self.reason = reason


class TenantBudgets:
    """Continuous-refill token buckets, one per tenant (lazily made)."""

    def __init__(self, capacity: float = 8.0,
                 refill_per_s: float = 2.0,
                 clock: Callable[[], float] = time.monotonic):
        if capacity <= 0 or refill_per_s < 0:
            raise ValueError("capacity must be > 0, refill_per_s >= 0")
        self.capacity = float(capacity)
        self.refill_per_s = float(refill_per_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._buckets: Dict[str, list] = {}  # tenant -> [tokens, t_last]

    def acquire(self, tenant: str, cost: float = 1.0) -> None:
        """Spend ``cost`` tokens or raise :class:`Rejected`."""
        now = self._clock()
        with self._lock:
            b = self._buckets.setdefault(
                tenant, [self.capacity, now])
            b[0] = min(self.capacity,
                       b[0] + (now - b[1]) * self.refill_per_s)
            b[1] = now
            if b[0] < cost:
                wait = (cost - b[0]) / self.refill_per_s \
                    if self.refill_per_s > 0 else float("inf")
                raise Rejected(
                    f"tenant {tenant!r} at budget "
                    f"({b[0]:.2f}/{self.capacity:g} tokens; "
                    f"~{wait:.1f}s to afford this request)",
                    reason="tenant-budget")
            b[0] -= cost

    def tokens(self, tenant: str) -> float:
        now = self._clock()
        with self._lock:
            b = self._buckets.get(tenant)
            if b is None:
                return self.capacity
            return min(self.capacity,
                       b[0] + (now - b[1]) * self.refill_per_s)


class AdmissionQueue:
    """Bounded admitted-but-unfinished request count + deadline shed.

    ``est_wait_s`` is only the cold-start seed: every completed
    request's wall feeds :meth:`observe`, and the live estimate is an
    exponentially-weighted moving average (``ewma_alpha`` weight on
    the newest wall).  ``retry_after_s`` hints and deadline sheds both
    read the EWMA, so backoff tracks what the server is *actually*
    doing right now."""

    def __init__(self, depth: int = 64,
                 est_wait_s: float = 0.25,
                 ewma_alpha: float = 0.2,
                 clock: Callable[[], float] = time.monotonic):
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        if not 0.0 < ewma_alpha <= 1.0:
            raise ValueError(f"ewma_alpha must be in (0, 1], "
                             f"got {ewma_alpha}")
        self.depth = depth
        self.seed_wait_s = float(est_wait_s)  # pre-observation seed
        self.ewma_alpha = float(ewma_alpha)
        self._ewma_s: Optional[float] = None
        self.observed = 0                     # walls folded into EWMA
        self._clock = clock
        self._lock = threading.Lock()
        self._admitted = 0
        self.peak = 0

    @property
    def est_wait_s(self) -> float:
        """Projected wait per queued item: the service-wall EWMA once
        anything has completed, the static seed before that."""
        ewma = self._ewma_s
        return self.seed_wait_s if ewma is None else ewma

    def observe(self, wall_s: float) -> None:
        """Fold one completed request's service wall into the EWMA."""
        wall_s = max(float(wall_s), 0.0)
        with self._lock:
            if self._ewma_s is None:
                self._ewma_s = wall_s
            else:
                a = self.ewma_alpha
                self._ewma_s = a * wall_s + (1.0 - a) * self._ewma_s
            self.observed += 1

    def admit(self, deadline_s: Optional[float] = None) -> None:
        """Admit or raise.  ``deadline_s`` is the client's remaining
        deadline for this request; a projected queue wait beyond it
        sheds the request NOW rather than serving a dead answer."""
        with self._lock:
            est = (self.seed_wait_s if self._ewma_s is None
                   else self._ewma_s)
            if self._admitted >= self.depth:
                raise Overloaded(
                    f"admission queue full ({self._admitted}/"
                    f"{self.depth}; est {est:.3f}s/query)",
                    retry_after_s=max(est, 0.05))
            projected = self._admitted * est
            if deadline_s is not None and projected > deadline_s:
                raise Rejected(
                    f"projected queue wait {projected:.2f}s exceeds "
                    f"request deadline {deadline_s:g}s "
                    f"({self._admitted} ahead)", reason="deadline")
            self._admitted += 1
            self.peak = max(self.peak, self._admitted)

    def release(self) -> None:
        with self._lock:
            if self._admitted > 0:
                self._admitted -= 1

    @property
    def admitted(self) -> int:
        with self._lock:
            return self._admitted

    def snapshot(self) -> Dict[str, object]:
        """Health/probe view of the queue's live state."""
        with self._lock:
            est = (self.seed_wait_s if self._ewma_s is None
                   else self._ewma_s)
            return {"depth": self.depth, "admitted": self._admitted,
                    "peak": self.peak, "est_wait_s": round(est, 6),
                    "observed": self.observed}


class CircuitBreaker:
    """Per-canonical-fingerprint breaker over the quarantine list.

    States per key: ``closed`` (normal) → ``open`` (quarantined plan
    shape; fast-fail until ``cooldown_s`` elapses) → ``half-open``
    (exactly one probe request allowed through) → ``closed`` on probe
    success / back to ``open`` on probe failure."""

    def __init__(self, quarantine, cooldown_s: float = 30.0,
                 clock: Callable[[], float] = time.monotonic):
        self.quarantine = quarantine
        self.cooldown_s = cooldown_s
        self._clock = clock
        self._lock = threading.Lock()
        self._opened_at: Dict[str, float] = {}
        self._probing: Dict[str, bool] = {}
        self.tripped = 0

    def state(self, key: str) -> str:
        with self._lock:
            if key not in self._opened_at:
                return "closed"
            if self._clock() - self._opened_at[key] < self.cooldown_s:
                return "open"
            return "half-open"

    def check(self, key: str) -> None:
        """Gate one request for ``key``: raise :class:`Rejected` while
        open; admit the single half-open probe after cooldown."""
        with self._lock:
            opened = self._opened_at.get(key)
            if opened is None:
                return
            age = self._clock() - opened
            if age < self.cooldown_s:
                raise Rejected(
                    f"circuit open for plan shape {key[:48]!r} "
                    f"(quarantined; retry in "
                    f"{self.cooldown_s - age:.1f}s)",
                    reason="circuit-open")
            if self._probing.get(key):
                raise Rejected(
                    f"circuit half-open for plan shape {key[:48]!r}: "
                    f"probe in flight", reason="circuit-open")
            self._probing[key] = True  # this request is the probe

    def note_success(self, key: str) -> None:
        with self._lock:
            self._opened_at.pop(key, None)
            self._probing.pop(key, None)

    def note_failure(self, key: str) -> bool:
        """Record a final (post-retry) failure; trips the breaker when
        the quarantine has poisoned the key.  Returns True on trip or
        re-open."""
        poisoned = self.quarantine is not None and \
            self.quarantine.is_quarantined(key)
        with self._lock:
            self._probing.pop(key, None)
            if not poisoned:
                return False
            first = key not in self._opened_at
            self._opened_at[key] = self._clock()
            if first:
                self.tripped += 1
            return True
