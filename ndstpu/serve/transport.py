"""Serve transports: AF_UNIX and TCP endpoints behind one grammar.

The PR 14 server spoke only a unix-domain socket — fine for one host,
useless for a replicated fleet whose clients and supervisor may not
share a filesystem.  This module is the one place endpoint strings are
parsed, listened on, and connected to; framing stays in
``protocol.py`` (length-prefixed JSON with ``MAX_FRAME_BYTES`` bounds)
so both transports speak byte-identical frames.

Endpoint grammar (accepted everywhere a socket path used to be):

``unix:/path/to.sock`` (or any bare path)
    AF_UNIX stream socket — the PR 14 default, unchanged.
``tcp:HOST:PORT`` (or bare ``HOST:PORT`` when HOST has no ``/``)
    TCP stream socket.  ``PORT`` 0 asks the kernel for an ephemeral
    port; the bound listener's real endpoint is recoverable via
    :func:`bound_endpoint`.

Multi-endpoint specs are comma-separated (``unix:/a.sock,tcp:h:9001``)
— the failover list a fleet client rotates through
(serve/client.py) and the listener set a server binds side by side.

Per-connection **read timeouts** bound how long a dead or wedged peer
can pin a reader thread: every accepted/connected socket gets
``settimeout`` from ``NDSTPU_SERVE_READ_TIMEOUT_S`` (default 600 s;
``0`` disables).  A timeout surfaces as ``socket.timeout`` — transient
by faults/taxonomy.py, so client retry loops treat it like any
connection fault.
"""

from __future__ import annotations

import dataclasses
import os
import socket
from typing import List, Optional

READ_TIMEOUT_ENV = "NDSTPU_SERVE_READ_TIMEOUT_S"
DEFAULT_READ_TIMEOUT_S = 600.0


def read_timeout_s() -> Optional[float]:
    """Per-connection read timeout; None disables (env set to 0)."""
    raw = os.environ.get(READ_TIMEOUT_ENV)
    if raw is None:
        return DEFAULT_READ_TIMEOUT_S
    try:
        val = float(raw)
    except ValueError:
        return DEFAULT_READ_TIMEOUT_S
    return val if val > 0 else None


@dataclasses.dataclass(frozen=True)
class Endpoint:
    """One parsed serve endpoint: ``unix`` path or ``tcp`` host:port."""

    kind: str                  # "unix" | "tcp"
    path: Optional[str] = None
    host: Optional[str] = None
    port: Optional[int] = None

    @property
    def spec(self) -> str:
        if self.kind == "unix":
            return f"unix:{self.path}"
        return f"tcp:{self.host}:{self.port}"

    def __str__(self) -> str:  # log-friendly
        return self.spec


def parse_endpoint(spec) -> Endpoint:
    """Parse one endpoint spec (an :class:`Endpoint` passes through)."""
    if isinstance(spec, Endpoint):
        return spec
    text = str(spec).strip()
    if not text:
        raise ValueError("empty serve endpoint spec")
    if text.startswith("unix:"):
        return Endpoint("unix", path=text[len("unix:"):])
    if text.startswith("tcp:"):
        rest = text[len("tcp:"):]
        host, sep, port = rest.rpartition(":")
        if not sep or not host:
            raise ValueError(f"tcp endpoint needs tcp:HOST:PORT "
                             f"(got {spec!r})")
        return Endpoint("tcp", host=host, port=int(port))
    # bare string: HOST:PORT when it looks like one, else a unix path
    if ":" in text and "/" not in text:
        host, _, port = text.rpartition(":")
        if port.isdigit():
            return Endpoint("tcp", host=host, port=int(port))
    return Endpoint("unix", path=text)


def parse_endpoints(spec) -> List[Endpoint]:
    """A comma-separated spec (or list of specs) -> endpoint list."""
    if isinstance(spec, (list, tuple)):
        out: List[Endpoint] = []
        for item in spec:
            out.extend(parse_endpoints(item))
        return out
    return [parse_endpoint(p) for p in str(spec).split(",")
            if p.strip()]


def listen(spec, backlog: int = 64) -> socket.socket:
    """Bind + listen on one endpoint; returns the listener socket."""
    ep = parse_endpoint(spec)
    if ep.kind == "unix":
        if os.path.exists(ep.path):
            os.unlink(ep.path)
        d = os.path.dirname(os.path.abspath(ep.path))
        os.makedirs(d, exist_ok=True)
        ls = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        ls.bind(ep.path)
    else:
        ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        ls.bind((ep.host, ep.port))
    ls.listen(backlog)
    return ls


def bound_endpoint(listener: socket.socket) -> Endpoint:
    """The endpoint a listener actually bound (resolves tcp port 0)."""
    if listener.family == socket.AF_UNIX:
        return Endpoint("unix", path=listener.getsockname())
    host, port = listener.getsockname()[:2]
    return Endpoint("tcp", host=host, port=port)


def connect(spec, connect_timeout_s: Optional[float] = None,
            read_timeout_s_override: Optional[float] = ...
            ) -> socket.socket:
    """Connect to one endpoint.  ``connect_timeout_s`` bounds only the
    connect itself; afterwards the socket carries the per-connection
    read timeout (override with ``read_timeout_s_override``; ``...``
    means use the env default, ``None`` means no timeout)."""
    ep = parse_endpoint(spec)
    if ep.kind == "unix":
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    else:
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    try:
        s.settimeout(connect_timeout_s)
        s.connect(ep.path if ep.kind == "unix" else (ep.host, ep.port))
        configure(s, read_timeout_s_override)
    except BaseException:
        s.close()
        raise
    return s


def configure(sock: socket.socket,
              read_timeout_s_override: Optional[float] = ...) -> None:
    """Apply the per-connection read timeout (server accept path and
    client connect path share this)."""
    timeout = read_timeout_s() if read_timeout_s_override is ... \
        else read_timeout_s_override
    sock.settimeout(timeout)
    if sock.family != socket.AF_UNIX:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)


def pick_free_port(host: str = "127.0.0.1") -> int:
    """An ephemeral TCP port that was free at probe time (fleet smoke
    convenience; production fleets pin ports in the fleet spec)."""
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    try:
        s.bind((host, 0))
        return s.getsockname()[1]
    finally:
        s.close()
